"""Follower read plane: validated-snapshot pointer, validated-seq
result caches, sharded subscription fanout, RPCSub retry, and the
account_tx retention-floor contract (ISSUE 10 / ROADMAP item 3)."""

from __future__ import annotations

import threading
import time

import jax
import pytest

jax.config.update("jax_platforms", "cpu")

from stellard_tpu.node.config import Config  # noqa: E402
from stellard_tpu.node.node import Node  # noqa: E402
from stellard_tpu.protocol.formats import TxType  # noqa: E402
from stellard_tpu.protocol.keys import KeyPair  # noqa: E402
from stellard_tpu.protocol.sfields import sfAmount, sfDestination  # noqa: E402
from stellard_tpu.protocol.stamount import STAmount  # noqa: E402
from stellard_tpu.protocol.sttx import SerializedTransaction  # noqa: E402
from stellard_tpu.rpc.handlers import Context, Role, dispatch  # noqa: E402
from stellard_tpu.rpc.readplane import ReadPlane, ResultCache  # noqa: E402


@pytest.fixture
def node():
    n = Node(Config(signature_backend="cpu")).setup()
    yield n
    n.stop()


def fund(n: Node, kp: KeyPair, drops: int = 1_000_000_000) -> None:
    master = n.master_keys
    root = n.ledger_master.current_ledger().account_root(master.account_id)
    from stellard_tpu.protocol.sfields import sfSequence

    tx = SerializedTransaction.build(
        TxType.ttPAYMENT, master.account_id, root[sfSequence], 10,
        {sfAmount: STAmount.from_drops(drops),
         sfDestination: kp.account_id},
    )
    tx.sign(master)
    ter, applied = n.submit(tx)
    assert applied, ter


def call(n: Node, method: str, role: Role = Role.ADMIN, **params) -> dict:
    return dispatch(Context(n, params, role), method)


class TestResultCache:
    def test_hit_miss_and_epoch_invalidation(self):
        c = ResultCache(capacity=4)
        assert c.get(5, "m", "k") is None
        c.on_new_seq(5)
        c.put(5, "m", "k", {"v": 1})
        assert c.get(5, "m", "k") == {"v": 1}
        # a stale-seq get/put never hits/lands
        assert c.get(4, "m", "k") is None
        c.put(4, "m", "k2", {"v": 2})
        assert c.get(5, "m", "k2") is None
        # new seq invalidates the whole generation
        c.on_new_seq(6)
        assert c.get(5, "m", "k") is None
        assert c.get(6, "m", "k") is None
        assert c.get_json()["invalidated"] == 1

    def test_capacity_bound(self):
        c = ResultCache(capacity=2)
        c.on_new_seq(1)
        c.put(1, "m", "a", {})
        c.put(1, "m", "b", {})
        c.put(1, "m", "c", {})  # over capacity: refused, not grown
        j = c.get_json()
        assert j["entries"] == 2 and j["overflow"] == 1

    def test_hit_returns_copy(self):
        c = ResultCache()
        c.on_new_seq(1)
        c.put(1, "m", "k", {"v": 1})
        got = c.get(1, "m", "k")
        got["status"] = "success"  # door annotation must not leak back
        assert "status" not in c.get(1, "m", "k")


class TestReadPlane:
    def test_publish_monotonic(self, node):
        rp = node.read_plane
        lcl1, _ = node.close_ledger()
        assert rp.snapshot() is not None
        assert rp.snapshot().seq == lcl1.seq
        lcl2, _ = node.close_ledger()
        assert rp.snapshot().seq == lcl2.seq
        # a historical republish never regresses the tip
        rp.publish(lcl1)
        assert rp.snapshot().seq == lcl2.seq

    def test_held_chain_lock_does_not_block_validated_reads(self, node):
        """THE acceptance pin: read RPCs against the last validated
        snapshot must complete while the chain lock (master lock AND
        the LedgerMaster lock) is held by a writer."""
        alice = KeyPair.from_passphrase("rp-alice")
        fund(node, alice)
        node.close_ledger()

        locked = threading.Event()
        release = threading.Event()

        def hold_locks():
            with node.ops.master_lock:
                with node.ledger_master._lock:
                    locked.set()
                    release.wait(timeout=30)

        t = threading.Thread(target=hold_locks, daemon=True)
        t.start()
        assert locked.wait(timeout=5)
        try:
            done = {}

            def read():
                for sel in ("validated", "closed", "current", None):
                    params = {"account": alice.human_account_id}
                    if sel is not None:
                        params["ledger_index"] = sel
                    r = dispatch(Context(node, params, Role.GUEST),
                                 "account_info")
                    done[sel] = r
            reader = threading.Thread(target=read, daemon=True)
            reader.start()
            reader.join(timeout=5)
            assert not reader.is_alive(), (
                "account_info blocked on the held chain lock"
            )
            for sel, r in done.items():
                assert "account_data" in r, (sel, r)
        finally:
            release.set()
            t.join(timeout=5)

    def test_dispatch_caches_validated_reads(self, node):
        alice = KeyPair.from_passphrase("rp-cache")
        fund(node, alice)
        node.close_ledger()
        params = {"account": alice.human_account_id,
                  "ledger_index": "validated"}
        r1 = dispatch(Context(node, dict(params), Role.GUEST),
                      "account_info")
        assert "account_data" in r1
        before = node.read_cache.get_json()["hits"]
        r2 = dispatch(Context(node, dict(params), Role.GUEST),
                      "account_info")
        assert r2["account_data"] == r1["account_data"]
        assert node.read_cache.get_json()["hits"] == before + 1
        # a new validated seq invalidates: next read is a miss again
        node.close_ledger()
        misses = node.read_cache.get_json()["misses"]
        dispatch(Context(node, dict(params), Role.GUEST), "account_info")
        assert node.read_cache.get_json()["misses"] > misses

    def test_quorum_lag_epoch_opens_on_validation(self, node):
        """On a quorum net the persist floor lands before the
        validation floor: the snapshot must stay behind min(persisted,
        validated) and the epoch must open when the validation
        arrives — not a full round later, and never before persist."""
        lcl, _ = node.close_ledger()
        rp = ReadPlane(cache=ResultCache())
        # persist floor arrives first (validations still in flight):
        # nothing serves yet
        rp.note_persisted(lcl)
        assert rp.snapshot() is None
        # validation floor catches up: epoch opens at the min
        rp.note_validated(lcl)
        assert rp.snapshot() is lcl
        assert rp.cache.get_json()["seq"] == lcl.seq
        # follower shape: validated-before-persisted must NOT advance
        # the snapshot past the persisted floor
        lcl2, _ = node.close_ledger()
        rp.note_validated(lcl2)
        assert rp.snapshot() is lcl
        rp.note_persisted(lcl2)
        assert rp.snapshot() is lcl2

    def test_account_tx_cached_only_when_bounded_by_validated(self, node):
        """account_tx's SQL index also holds closed-but-unvalidated
        ledgers — only windows explicitly bounded at or below the
        validated seq are pure functions of the snapshot."""
        alice = KeyPair.from_passphrase("rp-atx")
        fund(node, alice)
        node.close_ledger()
        val_seq = node.read_plane.snapshot().seq
        # unbounded window: never cached
        p = {"account": alice.human_account_id}
        dispatch(Context(node, dict(p), Role.GUEST), "account_tx")
        hits = node.read_cache.get_json()["hits"]
        dispatch(Context(node, dict(p), Role.GUEST), "account_tx")
        assert node.read_cache.get_json()["hits"] == hits
        # bounded at the validated seq: cached
        p = {"account": alice.human_account_id,
             "ledger_index_min": 1, "ledger_index_max": val_seq}
        r1 = dispatch(Context(node, dict(p), Role.GUEST), "account_tx")
        assert r1["transactions"]
        hits = node.read_cache.get_json()["hits"]
        r2 = dispatch(Context(node, dict(p), Role.GUEST), "account_tx")
        assert node.read_cache.get_json()["hits"] == hits + 1
        assert r2["transactions"] == r1["transactions"]

    def test_current_reads_not_cached(self, node):
        """A "current" read reflects the mutable open ledger — it must
        never come from the immutable validated-seq cache."""
        alice = KeyPair.from_passphrase("rp-cur")
        fund(node, alice)
        node.close_ledger()
        p = {"account": alice.human_account_id, "ledger_index": "current"}
        dispatch(Context(node, dict(p), Role.GUEST), "account_info")
        hits = node.read_cache.get_json()["hits"]
        dispatch(Context(node, dict(p), Role.GUEST), "account_info")
        assert node.read_cache.get_json()["hits"] == hits

    def test_follower_default_serves_validated(self, node):
        """With the follower's serve-validated default, selector-less
        reads resolve the validated snapshot (and cache)."""
        alice = KeyPair.from_passphrase("rp-def")
        fund(node, alice)
        node.close_ledger()
        node.serve_validated_default = True
        try:
            snap_seq = node.read_plane.snapshot().seq
            r = dispatch(
                Context(node, {"account": alice.human_account_id},
                        Role.GUEST),
                "account_info",
            )
            assert r["ledger_index"] == snap_seq
            hits = node.read_cache.get_json()["hits"]
            dispatch(
                Context(node, {"account": alice.human_account_id},
                        Role.GUEST),
                "account_info",
            )
            assert node.read_cache.get_json()["hits"] == hits + 1
        finally:
            node.serve_validated_default = False


class TestShardedFanout:
    def _mgr(self, node, **kw):
        from stellard_tpu.rpc.infosub import SubscriptionManager

        return SubscriptionManager(node.ops, **kw)

    def test_ordered_delivery_across_shards(self, node):
        from stellard_tpu.rpc.infosub import InfoSub

        mgr = self._mgr(node, shards=3)
        try:
            got: dict[int, list] = {}
            subs = []
            for i in range(8):
                lst: list = []
                sub = InfoSub(lst.append)
                got[sub.id] = lst
                mgr.subscribe_streams(sub, ["ledger"])
                subs.append(sub)
            for n_ev in range(50):
                msg = {"type": "ledgerClosed", "ledger_index": n_ev}
                for sub in subs:
                    mgr._deliver(sub, msg)
            assert mgr.flush(timeout=10.0)
            for sub in subs:
                seqs = [m["ledger_index"] for m in got[sub.id]]
                assert seqs == list(range(50)), (
                    f"sub {sub.id} out of order/lossy: {seqs[:10]}..."
                )
            j = mgr.get_json()
            assert j["delivered"] == 400 and j["dropped_events"] == 0
            assert j["fanout_lag_p99_ms"] >= 0.0
        finally:
            mgr.stop()

    def test_slow_consumer_bounded_and_evicted(self, node):
        """A consumer whose queue keeps overflowing (its shard worker
        wedged mid-send) drops OLDEST events within the cap and is
        evicted outright past the consecutive-drop threshold — it can
        never pin unbounded memory on the publish path."""
        from stellard_tpu.rpc.infosub import InfoSub

        mgr = self._mgr(node, shards=1, sendq_cap=4, evict_drops=3)
        try:
            gate = threading.Event()
            first_in = threading.Event()

            def slow_sink(msg):
                first_in.set()
                gate.wait(timeout=30)

            slow = InfoSub(slow_sink)
            mgr.subscribe_streams(slow, ["ledger"])

            # wedge the worker in the slow sink, then overflow its queue
            mgr._deliver(slow, {"type": "ledgerClosed", "i": -1})
            assert first_in.wait(timeout=5)
            for i in range(12):  # cap 4 → drops → eviction at 3 drops
                mgr._deliver(slow, {"type": "ledgerClosed", "i": i})
            assert len(slow.sendq) <= 4
            gate.set()
            assert mgr.flush(timeout=10.0)
            j = mgr.get_json()
            assert j["dropped_events"] >= 3
            assert j["slow_evicted"] == 1
            assert slow.evicted
            # the evicted sub is gone from the registry and further
            # publishes to it are no-ops
            with mgr._lock:
                assert slow.id not in mgr._subs
            mgr._deliver(slow, {"type": "ledgerClosed", "i": 99})
            assert mgr.get_json()["slow_evicted"] == 1
        finally:
            gate.set()
            mgr.stop()

    def test_publish_path_never_blocks_on_slow_consumer(self, node):
        """The close-path publisher only enqueues: a wedged subscriber
        must not stall _pub_ledger for everyone else."""
        from stellard_tpu.rpc.infosub import InfoSub

        mgr = self._mgr(node, shards=2, sendq_cap=8)
        try:
            gate = threading.Event()
            slow = InfoSub(lambda m: gate.wait(timeout=30))
            mgr.subscribe_streams(slow, ["ledger", "transactions"])
            alice = KeyPair.from_passphrase("fan-alice")
            fund(node, alice)
            t0 = time.perf_counter()
            node.close_ledger()  # fires _pub_ledger through mgr
            publish_s = time.perf_counter() - t0
            assert publish_s < 5.0, (
                f"publish stalled {publish_s:.1f}s behind a wedged sink"
            )
        finally:
            gate.set()
            mgr.stop()

    def test_inline_mode_unchanged(self, node):
        """shards=0 keeps the synchronous legacy path (tests and
        embedders that assert right after close)."""
        from stellard_tpu.rpc.infosub import InfoSub

        mgr = self._mgr(node)  # shards=0
        got: list = []
        sub = InfoSub(got.append)
        mgr.subscribe_streams(sub, ["ledger"])
        node.close_ledger()
        assert any(m.get("type") == "ledgerClosed" for m in got)


class TestRpcSubRetry:
    def _listener(self, fail_first: int, status_after: int = 200):
        import http.server

        state = {"calls": 0, "bodies": []}
        delivered = threading.Event()

        class Sink(http.server.BaseHTTPRequestHandler):
            def do_POST(self):
                import json as _json

                state["calls"] += 1
                n = int(self.headers.get("Content-Length", "0"))
                body = self.rfile.read(n)
                if state["calls"] <= fail_first:
                    self.send_response(500)
                    self.end_headers()
                    return
                state["bodies"].append(_json.loads(body))
                delivered.set()
                self.send_response(status_after)
                self.end_headers()
                self.wfile.write(b"{}")

            def log_message(self, *a):
                pass

        srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Sink)
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        return srv, state, delivered

    def test_retry_with_backoff_then_delivery(self):
        from stellard_tpu.rpc.rpcsub import RpcSub

        srv, state, delivered = self._listener(fail_first=2)
        try:
            sub = RpcSub(f"http://127.0.0.1:{srv.server_port}/",
                         max_retries=5, backoff_base=0.05,
                         backoff_max=0.2)
            sub._enqueue({"type": "ledgerClosed", "ledger_index": 7})
            assert delivered.wait(timeout=15), "event never delivered"
            # the sender thread bumps `sent` after the HTTP roundtrip
            # completes — poll briefly
            deadline = time.monotonic() + 5
            while sub.stats["sent"] < 1 and time.monotonic() < deadline:
                time.sleep(0.02)
            assert state["calls"] == 3  # 2 failures + 1 success
            assert sub.stats["retries"] == 2
            assert sub.stats["sent"] == 1
            assert sub.stats["dropped"] == 0
            ev = state["bodies"][0]["params"][0]
            assert ev["seq"] == 1 and ev["ledger_index"] == 7
            sub.close()
        finally:
            srv.shutdown()

    def test_retries_exhausted_drops_and_evicts(self):
        from stellard_tpu.rpc.rpcsub import RpcSub

        dead = threading.Event()
        # a port nothing listens on: every POST fails instantly
        sub = RpcSub("http://127.0.0.1:9/", max_retries=1,
                     backoff_base=0.01, backoff_max=0.02)
        sub.EVICT_FAILURES = 2
        sub.on_dead = dead.set
        for i in range(3):
            sub._enqueue({"type": "ledgerClosed", "ledger_index": i})
        assert dead.wait(timeout=15), "on_dead never fired"
        assert sub.stats["dropped"] >= 2
        assert sub.stats["retries"] >= 1
        sub.close()

    def test_order_preserved_across_retry(self):
        from stellard_tpu.rpc.rpcsub import RpcSub

        srv, state, delivered = self._listener(fail_first=1)
        try:
            sub = RpcSub(f"http://127.0.0.1:{srv.server_port}/",
                         max_retries=3, backoff_base=0.05,
                         backoff_max=0.1)
            sub._enqueue({"type": "a"})
            sub._enqueue({"type": "b"})
            deadline = time.monotonic() + 15
            while len(state["bodies"]) < 2 and time.monotonic() < deadline:
                time.sleep(0.05)
            seqs = [b["params"][0]["seq"] for b in state["bodies"]]
            assert seqs == [1, 2], f"retry reordered events: {seqs}"
            sub.close()
        finally:
            srv.shutdown()


class TestAccountTxRetentionFloor:
    def _flood_closes(self, node, n_closes=4):
        alice = KeyPair.from_passphrase("floor-alice")
        fund(node, alice)
        node.close_ledger()
        for _ in range(n_closes - 1):
            fund(node, alice, drops=1_000_000)
            node.close_ledger()
        return alice

    def test_marker_below_floor_errors(self, node):
        alice = self._flood_closes(node)
        node.txdb.trim_below(4)
        r = call(node, "account_tx", account=alice.human_account_id,
                 marker={"ledger": 2, "seq": 0})
        assert r.get("error") == "lgrIdxInvalid", r
        # backward paging resuming below the floor errors too
        r = call(node, "account_tx", account=alice.human_account_id,
                 forward=False, marker={"ledger": 3, "seq": 0})
        assert r.get("error") == "lgrIdxInvalid", r

    def test_window_below_floor_errors(self, node):
        alice = self._flood_closes(node)
        node.txdb.trim_below(4)
        r = call(node, "account_tx", account=alice.human_account_id,
                 ledger_index_min=1, ledger_index_max=3)
        assert r.get("error") == "lgrIdxInvalid", r

    def test_straddling_window_clamps_and_reports_floor(self, node):
        """A window straddling the floor serves what exists and echoes
        the EFFECTIVE minimum — a pager can see the truncation instead
        of reading a quietly complete-looking history."""
        alice = self._flood_closes(node)
        node.txdb.trim_below(4)
        r = call(node, "account_tx", account=alice.human_account_id,
                 ledger_index_min=1, ledger_index_max=10)
        assert "error" not in r, r
        assert r["ledger_index_min"] == 4, r["ledger_index_min"]
        for t in r["transactions"]:
            assert t["tx"]["ledger_index"] >= 4

    def test_failed_trim_does_not_raise_floor(self, node):
        alice = self._flood_closes(node)
        node.txdb.close()
        try:
            node.txdb.trim_below(4)
        except Exception:
            pass
        assert node.txdb.retain_floor == 0

    def test_valid_paging_above_floor_still_works(self, node):
        alice = self._flood_closes(node)
        node.txdb.trim_below(4)
        r = call(node, "account_tx", account=alice.human_account_id)
        assert "transactions" in r and r["transactions"], r
        for t in r["transactions"]:
            assert t["tx"]["hash"]
        # a marker AT/above the floor resumes cleanly
        r = call(node, "account_tx", account=alice.human_account_id,
                 marker={"ledger": 4, "seq": 0})
        assert "error" not in r, r

    def test_no_floor_no_gate(self, node):
        alice = self._flood_closes(node)
        r = call(node, "account_tx", account=alice.human_account_id,
                 marker={"ledger": 1, "seq": 0})
        assert "error" not in r, r


class TestFollowerFlag:
    def test_follower_requires_networked(self):
        with pytest.raises(ValueError, match="follower"):
            Node(Config(node_mode="follower", standalone=True))

    def test_follower_validator_never_rounds(self):
        from stellard_tpu.node.validator import ValidatorNode

        class _Adapter:
            def request_ledger_data(self, msg):
                pass

        kp = KeyPair.from_passphrase("fol-v")
        vn = ValidatorNode(
            key=kp, unl={kp.public}, adapter=_Adapter(), quorum=1,
            network_time=lambda: 0, follower=True,
        )
        vn.start(KeyPair.from_passphrase("masterpassphrase").account_id)
        assert vn.round is None
        assert vn.proposing is False
        assert vn.validator_state == "follower"
        vn.begin_round()
        assert vn.round is None
        j = vn.follower_json()
        assert j["ledgers_ingested"] == 0

    def test_config_rejects_unknown_mode(self):
        with pytest.raises(ValueError, match="mode"):
            Config.from_ini("[node]\nmode=observer\n")
