"""Over-the-wire API tests: HTTP JSON-RPC + WebSocket doors.

The shape of the reference's JS tests (test/jsonrpc-test.js,
test/websocket-test.js): spin a standalone node with real sockets,
drive it via the client API, assert on responses and streams.
"""

from __future__ import annotations

import base64
import hashlib
import json
import os
import socket
import struct
import urllib.request

import pytest

from stellard_tpu.node import Config, Node
from stellard_tpu.protocol.keys import KeyPair


@pytest.fixture(scope="module")
def node():
    cfg = Config()
    cfg.rpc_port = 0  # ephemeral
    cfg.websocket_port = 0
    n = Node(cfg).setup().serve()
    yield n
    n.stop()


def rpc(node: Node, method: str, **params) -> dict:
    url = f"http://127.0.0.1:{node.http_server.port}/"
    body = json.dumps({"method": method, "params": [params]}).encode()
    req = urllib.request.Request(
        url, data=body, headers={"Content-Type": "application/json"}
    )
    with urllib.request.urlopen(req, timeout=10) as resp:
        return json.load(resp)["result"]


class WsClient:
    """Minimal RFC 6455 client for tests."""

    def __init__(self, port: int):
        self.sock = socket.create_connection(("127.0.0.1", port), timeout=10)
        key = base64.b64encode(os.urandom(16)).decode()
        self.sock.sendall(
            (
                f"GET / HTTP/1.1\r\nHost: localhost\r\nUpgrade: websocket\r\n"
                f"Connection: Upgrade\r\nSec-WebSocket-Key: {key}\r\n"
                f"Sec-WebSocket-Version: 13\r\n\r\n"
            ).encode()
        )
        buf = b""
        while b"\r\n\r\n" not in buf:
            buf += self.sock.recv(4096)
        assert b"101" in buf.split(b"\r\n")[0]
        accept = base64.b64encode(
            hashlib.sha1(
                (key + "258EAFA5-E914-47DA-95CA-C5AB0DC85B11").encode()
            ).digest()
        ).decode()
        assert accept.encode() in buf

    def send(self, obj: dict) -> None:
        payload = json.dumps(obj).encode()
        mask = os.urandom(4)
        head = bytes([0x81])
        n = len(payload)
        if n < 126:
            head += bytes([0x80 | n])
        elif n < 65536:
            head += bytes([0x80 | 126]) + struct.pack(">H", n)
        else:
            head += bytes([0x80 | 127]) + struct.pack(">Q", n)
        masked = bytes(b ^ mask[i & 3] for i, b in enumerate(payload))
        self.sock.sendall(head + mask + masked)

    def _read_exact(self, n: int) -> bytes:
        out = b""
        while len(out) < n:
            chunk = self.sock.recv(n - len(out))
            if not chunk:
                raise ConnectionError("closed")
            out += chunk
        return out

    def recv(self) -> dict:
        b1, b2 = self._read_exact(2)
        n = b2 & 0x7F
        if n == 126:
            (n,) = struct.unpack(">H", self._read_exact(2))
        elif n == 127:
            (n,) = struct.unpack(">Q", self._read_exact(8))
        payload = self._read_exact(n)
        opcode = b1 & 0x0F
        if opcode == 0x9:  # ping → pong, keep reading
            return self.recv()
        return json.loads(payload)

    def call(self, command: str, **params) -> dict:
        params["command"] = command
        params.setdefault("id", 1)
        self.send(params)
        while True:
            msg = self.recv()
            if msg.get("type") == "response":
                return msg

    def close(self):
        self.sock.close()


class TestHttpDoor:
    def test_server_info(self, node):
        r = rpc(node, "server_info")
        assert r["status"] == "success"
        assert r["info"]["server_state"] == "full"

    def test_submit_and_close_flow(self, node):
        alice = KeyPair.from_passphrase("http-alice")
        r = rpc(
            node, "submit",
            secret="masterpassphrase",
            tx_json={
                "TransactionType": "Payment",
                "Account": node.master_keys.human_account_id,
                "Destination": alice.human_account_id,
                "Amount": "1000000000",
            },
        )
        assert r["engine_result"] == "tesSUCCESS", r
        r = rpc(node, "ledger_accept")
        assert r["status"] == "success"
        r = rpc(node, "account_info", account=alice.human_account_id)
        assert r["account_data"]["Balance"] == "1000000000"

    def test_error_shape(self, node):
        r = rpc(node, "account_info", account="garbage")
        assert r["status"] == "error"
        assert r["error"] == "actMalformed"

    def test_unknown_method(self, node):
        r = rpc(node, "definitely_not_a_method")
        assert r["error"] == "unknownCmd"


class TestWsDoor:
    def test_command_response(self, node):
        ws = WsClient(node.ws_server.port)
        try:
            resp = ws.call("ledger_current")
            assert resp["status"] == "success"
            assert "ledger_current_index" in resp["result"]
        finally:
            ws.close()

    def test_subscribe_stream_delivery(self, node):
        ws = WsClient(node.ws_server.port)
        try:
            resp = ws.call("subscribe", streams=["ledger", "transactions"])
            assert resp["status"] == "success"
            assert "ledger_index" in resp["result"]

            bob = KeyPair.from_passphrase("ws-bob")
            r = rpc(
                node, "submit",
                secret="masterpassphrase",
                tx_json={
                    "TransactionType": "Payment",
                    "Account": node.master_keys.human_account_id,
                    "Destination": bob.human_account_id,
                    "Amount": "500000000",
                },
            )
            assert r["engine_result"] == "tesSUCCESS"
            rpc(node, "ledger_accept")

            got_types = set()
            ws.sock.settimeout(10)
            while not {"ledgerClosed", "transaction"} <= got_types:
                msg = ws.recv()
                if "type" in msg:
                    got_types.add(msg["type"])
            assert {"ledgerClosed", "transaction"} <= got_types
        finally:
            ws.close()

    def test_wallet_propose_over_ws(self, node):
        ws = WsClient(node.ws_server.port)
        try:
            resp = ws.call("wallet_propose", passphrase="ws-carol")
            kp = KeyPair.from_passphrase("ws-carol")
            assert resp["result"]["account_id"] == kp.human_account_id
        finally:
            ws.close()


class TestHackBattery:
    """Adversarial client behavior (reference: test/hack-test.js intent):
    malformed bodies, wrong methods, junk blobs, abusive frames — the
    doors must answer with clean errors and KEEP SERVING."""

    def _raw_http(self, node, payload: bytes, method=b"POST",
                  content_type=b"application/json") -> bytes:
        s = socket.create_connection(("127.0.0.1", node.http_server.port),
                                     timeout=10)
        try:
            head = (
                method + b" / HTTP/1.1\r\nHost: x\r\nContent-Type: "
                + content_type
                + b"\r\nContent-Length: " + str(len(payload)).encode()
                + b"\r\nConnection: close\r\n\r\n"
            )
            s.sendall(head + payload)
            buf = b""
            while True:
                chunk = s.recv(65536)
                if not chunk:
                    return buf
                buf += chunk
        finally:
            s.close()

    def test_invalid_json_body(self, node):
        resp = self._raw_http(node, b"{this is not json")
        assert resp.split(b"\r\n")[0].endswith((b"400 Bad Request", b"200 OK"))
        assert b"error" in resp

    def test_wrong_http_method(self, node):
        # GET is the health probe; anything else must not crash the door
        resp = self._raw_http(node, b"", method=b"GET")
        assert b"200 OK" in resp.split(b"\r\n")[0]
        resp = self._raw_http(node, b"x", method=b"BREW")
        assert b"HTTP/1.1" in resp  # clean HTTP error, not a hang/crash

    def test_params_of_wrong_type(self, node):
        # params must be a list-of-objects; hand it scalars and junk
        for params in (42, "x", [1, 2, 3], {"not": "a list"}):
            body = json.dumps({"method": "server_info", "params": params})
            resp = self._raw_http(node, body.encode())
            assert b"HTTP/1.1" in resp  # server answered, didn't die

    def test_garbage_tx_blob(self, node):
        r = rpc(node, "submit", tx_blob="zznothex")
        assert r["error"] == "invalidTransaction"
        r = rpc(node, "submit", tx_blob="00" * 40)  # hex but not a tx
        assert r["error"] == "invalidTransaction"

    def test_tampered_signed_blob_rejected(self, node):
        alice = KeyPair.from_passphrase("hack-alice")
        r = rpc(
            node, "sign",
            secret="masterpassphrase",
            tx_json={
                "TransactionType": "Payment",
                "Account": node.master_keys.human_account_id,
                "Destination": alice.human_account_id,
                "Amount": "1000000",
            },
        )
        blob = bytearray(bytes.fromhex(r["tx_blob"]))
        blob[-3] ^= 0x40  # flip a bit near the tail (inside sig/amount)
        r2 = rpc(node, "submit", tx_blob=bytes(blob).hex().upper())
        assert r2.get("engine_result") != "tesSUCCESS"

    def test_overflow_amount_rejected(self, node):
        r = rpc(
            node, "submit",
            secret="masterpassphrase",
            tx_json={
                "TransactionType": "Payment",
                "Account": node.master_keys.human_account_id,
                "Destination": KeyPair.from_passphrase("hack-bob").human_account_id,
                "Amount": str(10**30),  # > total coin supply
            },
        )
        assert r["status"] == "error" or r.get("engine_result") != "tesSUCCESS"

    def test_ws_junk_frames_then_clean_close(self, node):
        # raw bytes that are not a valid websocket handshake
        s = socket.create_connection(("127.0.0.1", node.ws_server.port),
                                     timeout=10)
        try:
            s.sendall(b"\x00\xff" * 64)
            s.settimeout(2)
            try:
                while s.recv(4096):
                    pass
            except (TimeoutError, OSError):
                pass
        finally:
            s.close()
        # the door still serves real clients
        ws = WsClient(node.ws_server.port)
        try:
            assert ws.call("ping")["status"] == "success"
        finally:
            ws.close()

    def test_doors_survive_the_battery(self, node):
        assert rpc(node, "server_info")["status"] == "success"


class TestPathFindSubscription:
    def test_live_path_updates_on_close(self, node):
        """path_find create over WS registers a live request; every
        ledger close pushes a fresh full_reply (PathRequests role)."""
        ws = WsClient(node.ws_server.port)
        try:
            resp = ws.call(
                "path_find",
                subcommand="create",
                source_account=node.master_keys.human_account_id,
                destination_account=KeyPair.from_passphrase("pf-alice").human_account_id,
                destination_amount={
                    "currency": "USD",
                    "issuer": node.master_keys.human_account_id,
                    "value": "5",
                },
            )
            assert resp["status"] == "success", resp
            rid = resp["result"]["id"]

            ws.sock.settimeout(10)

            def next_path_find():
                while True:
                    msg = ws.recv()
                    if msg.get("type") == "path_find":
                        return msg

            # first update answers at PATH_SEARCH_FAST and is marked
            # partial; the next one runs the full search level
            # (reference: PathRequest.cpp:370-379 + full_reply contract)
            rpc(node, "ledger_accept")
            msg = next_path_find()
            assert msg["id"] == rid
            assert msg["full_reply"] is False
            assert "alternatives" in msg

            rpc(node, "ledger_accept")
            msg = next_path_find()
            assert msg["id"] == rid
            assert msg["full_reply"] is True
            assert "alternatives" in msg

            closed = ws.call("path_find", subcommand="close", id=rid)
            assert closed["result"]["closed"] is True
        finally:
            ws.close()


class TestSecureDoors:
    """[rpc_secure]/[websocket_secure] — TLS-terminated API doors
    (reference Config.cpp:475-492; WSDoor/RPCDoor SSL). The cert is the
    node's auto-generated self-signed transport cert, so clients connect
    with verification off, as the reference's own tooling does for
    loopback admin."""

    @pytest.fixture(scope="class")
    def secure_node(self):
        cfg = Config()
        cfg.rpc_port = 0
        cfg.websocket_port = 0
        cfg.rpc_secure = 1
        cfg.websocket_secure = 1
        n = Node(cfg).setup().serve()
        yield n
        n.stop()

    @staticmethod
    def _client_ctx():
        import ssl

        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
        ctx.check_hostname = False
        ctx.verify_mode = ssl.CERT_NONE
        return ctx

    def test_https_rpc(self, secure_node):
        url = f"https://127.0.0.1:{secure_node.http_server.port}/"
        body = json.dumps(
            {"method": "server_info", "params": [{}]}
        ).encode()
        req = urllib.request.Request(
            url, data=body, headers={"Content-Type": "application/json"}
        )
        with urllib.request.urlopen(
            req, timeout=10, context=self._client_ctx()
        ) as resp:
            result = json.load(resp)["result"]
        assert result["status"] == "success"
        assert "info" in result

    def test_plain_http_refused_on_secure_door(self, secure_node):
        import urllib.error

        url = f"http://127.0.0.1:{secure_node.http_server.port}/"
        body = json.dumps(
            {"method": "server_info", "params": [{}]}
        ).encode()
        req = urllib.request.Request(
            url, data=body, headers={"Content-Type": "application/json"}
        )
        with pytest.raises(Exception):
            with urllib.request.urlopen(req, timeout=5) as resp:
                json.load(resp)

    def test_wss_command(self, secure_node):
        import base64
        import os
        import socket
        import ssl

        raw = socket.create_connection(
            ("127.0.0.1", secure_node.ws_server.port), timeout=10
        )
        s = self._client_ctx().wrap_socket(raw)
        try:
            key = base64.b64encode(os.urandom(16)).decode()
            s.sendall(
                (
                    f"GET / HTTP/1.1\r\nHost: x\r\nUpgrade: websocket\r\n"
                    f"Connection: Upgrade\r\nSec-WebSocket-Key: {key}\r\n"
                    f"Sec-WebSocket-Version: 13\r\n\r\n"
                ).encode()
            )
            resp = b""
            while b"\r\n\r\n" not in resp:
                resp += s.recv(4096)
            assert b"101" in resp.split(b"\r\n", 1)[0]
            # one masked text frame: {"command": "ping", "id": 1}
            payload = json.dumps({"command": "ping", "id": 1}).encode()
            mask = os.urandom(4)
            frame = bytes([0x81, 0x80 | len(payload)]) + mask + bytes(
                b ^ mask[i % 4] for i, b in enumerate(payload)
            )
            s.sendall(frame)
            hdr = s.recv(2)
            assert hdr and (hdr[0] & 0x0F) == 1  # text frame back
            ln = hdr[1] & 0x7F
            if ln == 126:
                ln = struct.unpack(">H", s.recv(2))[0]
            data = b""
            while len(data) < ln:
                data += s.recv(ln - len(data))
            msg = json.loads(data)
            assert msg.get("id") == 1
            assert msg.get("status") == "success"
        finally:
            s.close()


class TestRpcSubUrlCallbacks:
    """subscribe with a `url` (reference: Subscribe.cpp:34-80 + RPCSub):
    the server POSTs matching events to the client's HTTP listener as
    JSON-RPC {"method": "event"} requests with increasing seq."""

    def test_url_subscription_end_to_end(self):
        import http.server
        import json as _json
        import threading
        import time

        import jax

        jax.config.update("jax_platforms", "cpu")
        from stellard_tpu.node.config import Config
        from stellard_tpu.node.node import Node
        from stellard_tpu.rpc.handlers import Context, Role, dispatch

        received: list = []
        got_one = threading.Event()

        class Sink(http.server.BaseHTTPRequestHandler):
            def do_POST(self):
                n = int(self.headers.get("Content-Length", "0"))
                received.append(
                    (_json.loads(self.rfile.read(n)),
                     self.headers.get("Authorization"))
                )
                got_one.set()
                self.send_response(200)
                self.end_headers()
                self.wfile.write(b"{}")

            def log_message(self, *a):  # quiet
                pass

        listener = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Sink)
        threading.Thread(target=listener.serve_forever, daemon=True).start()
        url = f"http://127.0.0.1:{listener.server_port}/"

        node = Node(Config(signature_backend="cpu")).setup().serve()
        try:
            # guest may not register a url sub
            r = dispatch(Context(node, {"url": url, "streams": ["ledger"]},
                                 Role.GUEST), "subscribe")
            assert r.get("error") == "noPermission"
            # bad scheme is invalidParams
            r = dispatch(Context(node, {"url": "ftp://x/",
                                        "streams": ["ledger"]},
                                 Role.ADMIN), "subscribe")
            assert r.get("error") == "invalidParams"

            r = dispatch(Context(node, {
                "url": url, "streams": ["ledger"],
                "url_username": "u", "url_password": "p",
            }, Role.ADMIN), "subscribe")
            assert not r.get("error"), r
            node.ops.accept_ledger()
            assert got_one.wait(timeout=20), "no callback delivered"
            body, auth = received[0]
            assert body["method"] == "event"
            ev = body["params"][0]
            assert ev["type"] == "ledgerClosed" and ev["seq"] == 1
            assert auth and auth.startswith("Basic ")

            # second close: seq increases on the same subscription
            got_one.clear()
            node.ops.accept_ledger()
            assert got_one.wait(timeout=20)
            assert received[-1][0]["params"][0]["seq"] == 2

            # unsubscribing an unknown url must error, never create
            r = dispatch(Context(node, {"url": "http://127.0.0.1:1/",
                                        "streams": ["ledger"]},
                                 Role.ADMIN), "unsubscribe")
            assert r.get("error") == "invalidParams"

            # unsubscribe via url: no further deliveries, entry pruned
            r = dispatch(Context(node, {"url": url, "streams": ["ledger"]},
                                 Role.ADMIN), "unsubscribe")
            assert not r.get("error"), r
            assert node.subs.rpc_sub_lookup(url) is None, (
                "emptied url subscription must be pruned"
            )
            got_one.clear()
            node.ops.accept_ledger()
            assert not got_one.wait(timeout=3)
        finally:
            node.stop()
            listener.shutdown()
