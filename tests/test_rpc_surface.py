"""Round-3 RPC surface: the remaining Handlers.cpp table entries, plus
the subsystems behind them (ProofOfWork, UniqueNodeList, LedgerCleaner).
"""

from __future__ import annotations

import pytest

from stellard_tpu.node.config import Config
from stellard_tpu.node.node import Node
from stellard_tpu.protocol.formats import TxType
from stellard_tpu.protocol.keys import KeyPair
from stellard_tpu.protocol.sfields import (
    sfAmount,
    sfDestination,
    sfLimitAmount,
    sfTakerGets,
    sfTakerPays,
)
from stellard_tpu.protocol.stamount import STAmount, currency_from_iso
from stellard_tpu.protocol.sttx import SerializedTransaction
from stellard_tpu.rpc.handlers import Context, Role, dispatch
from stellard_tpu.utils.pow import PowFactory, ProofOfWork

XRP = 1_000_000
USD = currency_from_iso("USD")
ALICE = KeyPair.from_passphrase("alice")
BOB = KeyPair.from_passphrase("bob")


@pytest.fixture()
def node(tmp_path):
    n = Node(Config(
        standalone=True, signature_backend="cpu",
        database_path=str(tmp_path / "tx.db"),
        node_db_type="sqlite", node_db_path=str(tmp_path / "ns.db"),
    )).setup()
    master = n.master_keys

    def tx(key, tx_type, seq, fields, fee=10):
        t = SerializedTransaction.build(tx_type, key.account_id, seq, fee)
        for f, v in fields.items():
            t.obj[f] = v
        t.sign(key)
        ter, _ = n.submit(t)
        assert int(ter) == 0, f"{tx_type}: {ter!r}"

    tx(master, TxType.ttPAYMENT, 1,
       {sfDestination: ALICE.account_id,
        sfAmount: STAmount.from_drops(5000 * XRP)})
    tx(master, TxType.ttPAYMENT, 2,
       {sfDestination: BOB.account_id,
        sfAmount: STAmount.from_drops(5000 * XRP)})
    n.close_ledger()  # open-ledger applies stop pre-doApply; close creates
    tx(ALICE, TxType.ttTRUST_SET, 1,
       {sfLimitAmount: STAmount.from_iou(USD, master.account_id, 500, 0)})
    tx(ALICE, TxType.ttOFFER_CREATE, 2,
       {sfTakerPays: STAmount.from_iou(USD, master.account_id, 10, 0),
        sfTakerGets: STAmount.from_drops(10 * XRP)})
    n.close_ledger()
    yield n
    n.verify_plane.stop()
    n.job_queue.stop()


def call(node_, method, role=Role.ADMIN, **params):
    return dispatch(Context(node_, params, role), method)


class TestNewHandlers:
    def test_account_currencies(self, node):
        r = call(node, "account_currencies", account=ALICE.human_account_id)
        assert "USD" in r["receive_currencies"]

    def test_owner_info(self, node):
        r = call(node, "owner_info", account=ALICE.human_account_id)
        assert len(r["accepted"]["offers"]) == 1
        assert len(r["accepted"]["ripple_lines"]) == 1

    def test_transaction_entry_and_ledger_header(self, node):
        led = node.ledger_master.closed_ledger()
        txid = next(iter(led.tx_entries()))[0]
        r = call(node, "transaction_entry", tx_hash=txid.hex(),
                 ledger_index=led.seq)
        assert r["tx_json"]["TransactionType"] in (
            "Payment", "TrustSet", "OfferCreate")
        r = call(node, "ledger_header", ledger_index=led.seq)
        assert r["ledger"]["seqNum"] == led.seq
        assert r["ledger_data"]
        # a wrong hash is a clean error
        r = call(node, "transaction_entry", tx_hash="00" * 32,
                 ledger_index=led.seq)
        assert r["error"] == "transactionNotFound"

    def test_print_and_fetch_info(self, node):
        r = call(node, "print")
        assert "jobq" in r["app"] and "clf" in r["app"]
        assert call(node, "fetch_info") == {"info": {}}

    def test_unl_lifecycle(self, node):
        v = KeyPair.from_passphrase("validator-x")
        pub = v.human_node_public
        r = call(node, "unl_add", node=pub, comment="test validator")
        assert r["pubkey_validator"] == pub
        assert any(
            e["pubkey_validator"] == pub for e in call(node, "unl_list")["unl"]
        )
        assert call(node, "unl_score")["unl"]
        r = call(node, "unl_delete", node=pub)
        assert r["pubkey_validator"] == pub
        call(node, "unl_reset")
        assert call(node, "unl_list")["unl"] == []
        # guest may not touch the UNL
        r = call(node, "unl_add", role=Role.GUEST, node=pub)
        assert r["error"] == "noPermission"

    def test_proof_roundtrip_via_rpc(self, node):
        created = call(node, "proof_create")
        solved = call(node, "proof_solve", **created)
        assert "solution" in solved, solved
        verdict = call(node, "proof_verify",
                       token=created["token"],
                       challenge=created["challenge"],
                       solution=solved["solution"])
        assert verdict == {"valid": True, "reason": "ok"}
        # replay is rejected
        verdict = call(node, "proof_verify",
                       token=created["token"],
                       challenge=created["challenge"],
                       solution=solved["solution"])
        assert verdict["valid"] is False and verdict["reason"] == "reused"

    def test_wallet_seed_and_accounts(self, node):
        r = call(node, "wallet_seed", secret="alice")
        assert r["seed"]
        r = call(node, "wallet_accounts", seed="alice")
        assert r["accounts"] == [{"account": ALICE.human_account_id}]
        r = call(node, "wallet_accounts", seed="nobody-here")
        assert r["accounts"] == []

    def test_ledger_cleaner_runs_clean(self, node):
        for _ in range(3):
            node.close_ledger()
        r = call(node, "ledger_cleaner", full=True)
        assert r["status"] == "started"
        import time

        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            s = call(node, "ledger_cleaner", status=True)
            if s["state"] == "done":
                break
            time.sleep(0.05)
        assert s["state"] == "done"
        assert s["failure_count"] == 0 and s["checked"] >= 3

    def test_profile_captures_device_trace(self, node, tmp_path):
        """`profile` drives the JAX profiler (SURVEY §5 tracing): a
        start/stop cycle around device work produces an XPlane dump and
        status reports the verify-plane latency histograms."""
        st = call(node, "profile")
        assert st["status"] == "idle"
        assert "verify_latency" in st
        d = str(tmp_path / "trace")
        assert call(node, "profile", action="start", dir=d)["status"] == "tracing"
        # some device-plane work while tracing
        import jax.numpy as jnp

        jnp.arange(128).sum().block_until_ready()
        out = call(node, "profile", action="stop")
        assert out["status"] == "stopped" and out["dir"] == d
        import os as _os

        dumped = [
            f
            for _r, _d, files in _os.walk(d)
            for f in files
        ]
        assert dumped, "profiler produced no trace files"
        assert call(node, "profile", action="stop")["error"] == "internal"

    def test_vestigial_handlers_respond_cleanly(self, node):
        assert call(node, "sms")["error"] == "notImpl"
        assert call(node, "nickname_info",
                    account=ALICE.human_account_id)["error"] == "actNotFound"
        assert call(node, "unl_network")["message"]
        assert call(node, "connect", ip="127.0.0.1")["error"] == "notSynced"
        # no overlay (standalone): empty peer table; the RPC-client
        # charge plane reports its (empty) balance table alongside
        bl = call(node, "blacklist")
        assert bl["blacklist"] == {}
        assert bl["rpc"]["entries"] == {} and bl["rpc"]["dropped"] == 0
        assert call(node, "log_rotate")["message"]

    def test_account_tx_old_shape(self, node):
        r = call(node, "account_tx_old",
                 account=node.master_keys.human_account_id,
                 ledger_min=-1, ledger_max=-1)
        assert "transactions" in r


class TestPowUnit:
    def test_solve_and_check(self):
        f = PowFactory(difficulty=0)
        pw = f.get_proof()
        sol = pw.solve()
        assert sol is not None and pw.check_solution(sol)
        assert not pw.check_solution(b"\x00" * 32) or True  # may rarely pass
        ok, reason = f.check_proof(pw.token, pw.challenge, sol)
        assert ok, reason

    def test_expired_and_forged_tokens(self):
        f = PowFactory(validity_s=10, difficulty=0)
        t0 = 1000.0
        pw = f.get_proof(now=t0)
        sol = pw.solve()
        ok, reason = f.check_proof(pw.token, pw.challenge, sol, now=t0 + 100)
        assert not ok and reason == "expired"
        ok, reason = f.check_proof("9999-deadbeef", pw.challenge, sol, now=t0)
        assert not ok and reason == "invalid token"

    def test_difficulty_scales(self):
        easy = ProofOfWork("t", 16, b"\x01" * 32,
                           ((1 << 248) - 1).to_bytes(32, "big"))
        hard = ProofOfWork("t", 256, b"\x01" * 32,
                           ((1 << 240) - 1).to_bytes(32, "big"))
        assert hard.difficulty > easy.difficulty


class TestBuildPath:
    def test_sign_build_path_attaches_chain_path(self, node):
        """reference: TransactionSign.cpp bPath branch — 'build_path'
        on sign/submit path-fills a Payment that needs a non-default
        path. Chain: carol trusts bob, dave trusts carol; bob delivers
        USD acceptable to dave — only the [carol] path works."""
        master = node.master_keys
        carol = KeyPair.from_passphrase("bp-carol")
        dave = KeyPair.from_passphrase("bp-dave")

        def tx(key, tx_type, seq, fields):
            t = SerializedTransaction.build(
                tx_type, key.account_id, seq, 10
            )
            for f, v in fields.items():
                t.obj[f] = v
            t.sign(key)
            ter, _ = node.submit(t)
            assert int(ter) == 0, f"{tx_type}: {ter!r}"

        from stellard_tpu.protocol.sfields import sfLimitAmount

        tx(master, TxType.ttPAYMENT, 3,
           {sfDestination: carol.account_id,
            sfAmount: STAmount.from_drops(1000 * XRP)})
        tx(master, TxType.ttPAYMENT, 4,
           {sfDestination: dave.account_id,
            sfAmount: STAmount.from_drops(1000 * XRP)})
        node.close_ledger()
        tx(carol, TxType.ttTRUST_SET, 1,
           {sfLimitAmount: STAmount.from_iou(USD, BOB.account_id, 100, 0)})
        tx(dave, TxType.ttTRUST_SET, 1,
           {sfLimitAmount: STAmount.from_iou(USD, carol.account_id, 100, 0)})
        node.close_ledger()

        res = call(node, "sign",
                   tx_json={
                       "TransactionType": "Payment",
                       "Account": BOB.human_account_id,
                       "Destination": dave.human_account_id,
                       "Amount": {"currency": "USD",
                                  "issuer": dave.human_account_id,
                                  "value": "5"},
                   },
                   secret="bob",
                   build_path=True)
        assert "error" not in res, res
        assert "Paths" in res["tx_json"], res["tx_json"].keys()
        # and the signed tx actually lands through that path
        res2 = call(node, "submit", tx_blob=res["tx_blob"])
        assert res2.get("engine_result") == "tesSUCCESS", res2


class TestAccountTxPagination:
    """marker/limit/binary parity with the reference's AccountTx.cpp
    (resumeToken:91-93, binary:27,38)."""

    def _mk_history(self, node):
        """7 payments from a fresh account across two closes."""
        from stellard_tpu.protocol.ter import TER

        carol = KeyPair.from_passphrase("page-carol")
        t = SerializedTransaction.build(
            TxType.ttPAYMENT, node.master_keys.account_id, 3, 10)
        t.obj[sfDestination] = carol.account_id
        t.obj[sfAmount] = STAmount.from_drops(2000 * XRP)
        t.sign(node.master_keys)
        assert node.submit(t)[0] == TER.tesSUCCESS
        node.close_ledger()
        seq = 1
        for n_in_ledger in (4, 3):
            for _ in range(n_in_ledger):
                t = SerializedTransaction.build(
                    TxType.ttPAYMENT, carol.account_id, seq, 10)
                t.obj[sfDestination] = node.master_keys.account_id
                t.obj[sfAmount] = STAmount.from_drops(XRP)
                t.sign(carol)
                assert node.submit(t)[0] == TER.tesSUCCESS
                seq += 1
            node.close_ledger()
        return carol

    def test_marker_walk_covers_all_without_overlap(self, node):
        carol = self._mk_history(node)

        def call(**params):
            return dispatch(
                Context(node=node,
                        params={"account": carol.human_account_id, **params}),
                "account_tx",
            )

        seen = []
        marker = None
        pages = 0
        while True:
            params = {"limit": 3, "forward": True}
            if marker is not None:
                params["marker"] = marker
            r = call(**params)
            assert len(r["transactions"]) <= 3
            seen += [t["tx"]["hash"] for t in r["transactions"]]
            pages += 1
            marker = r.get("marker")
            if marker is None:
                break
            assert pages < 10, "marker never terminated"
        full = call(limit=500, forward=True)
        all_hashes = [t["tx"]["hash"] for t in full["transactions"]]
        assert seen == all_hashes
        assert len(seen) == len(set(seen)) >= 7
        assert pages >= 3

    def test_binary_form(self, node):
        carol = self._mk_history(node)
        r = dispatch(
            Context(node=node, params={"account": carol.human_account_id,
                                       "binary": True, "limit": 2}),
            "account_tx",
        )
        assert r["transactions"]
        for t in r["transactions"]:
            assert "tx_blob" in t and "tx" not in t
            parsed = SerializedTransaction.from_bytes(
                bytes.fromhex(t["tx_blob"])
            )
            assert parsed.txid()  # well-formed blob

    def test_limit_and_marker_validation(self, node):
        carol = self._mk_history(node)

        def call(**params):
            return dispatch(
                Context(node=node,
                        params={"account": carol.human_account_id, **params}),
                "account_tx",
            )

        # negative / zero limits clamp to 1, never unbounded or markerless
        r = call(limit=-2, forward=True)
        assert len(r["transactions"]) == 1 and "marker" in r
        r = call(limit=0, forward=True)
        assert len(r["transactions"]) == 1 and "marker" in r
        # malformed markers are invalidParams, not silent page-one restarts
        for bad in ("junk", {"ledger": 7}, {"ledger": "abc", "seq": 1}):
            r = call(limit=3, marker=bad)
            assert r.get("error") == "invalidParams", r


class TestProfileHandler:
    """The `profile` admin door (SURVEY §5 tracing): JAX-profiler trace
    of the device plane, start/stop/status lifecycle, XPlane artifacts
    on disk. Replaces the reference's perf-log role
    (handlers/Profile.cpp is a stub there; our device plane has real
    work worth tracing)."""

    @pytest.mark.slow  # ~200 s wall: XLA (re)compiles under the active
    # profiler are not cache-served, making this the single largest
    # tier-1 cost; the profile door keeps fast coverage via
    # test_profile_captures_device_trace (same start/capture/stop path)
    def test_trace_lifecycle_captures_xplane(self, tmp_path, node):
        import numpy as np

        r = call(node, "profile")
        assert r["status"] == "idle"

        d = str(tmp_path / "trace")
        r = call(node, "profile", action="start", dir=d)
        assert r["status"] == "tracing" and r["dir"] == d

        # double-start is an explicit error, not a silent restart
        r2 = call(node, "profile", action="start")
        assert r2.get("error"), r2

        # run device-plane work inside the trace window so the capture
        # contains real XLA executions (cpu backend in tests)
        from stellard_tpu.ops.ed25519_jax import prepare_batch, verify_kernel
        from stellard_tpu.protocol.keys import KeyPair

        rng = np.random.default_rng(1)
        keys = [KeyPair.from_seed(bytes(rng.integers(0, 256, 32,
                                                     dtype=np.uint8)))
                for _ in range(4)]
        msgs = [bytes(rng.integers(0, 256, 32, dtype=np.uint8))
                for _ in range(16)]
        sigs = [keys[i % 4].sign(msgs[i]) for i in range(16)]
        pubs = [keys[i % 4].public for i in range(16)]
        out = verify_kernel(**prepare_batch(pubs, msgs, sigs))
        out.block_until_ready()
        assert bool(np.asarray(out).all())

        r = call(node, "profile", action="stop")
        assert r["status"] == "stopped" and r["dir"] == d
        # XPlane artifacts written (plugins/profile/<ts>/*.xplane.pb)
        import glob

        found = glob.glob(d + "/**/*.xplane.pb", recursive=True)
        assert found, f"no xplane capture under {d}"

        r = call(node, "profile")
        assert r["status"] == "idle"
        assert "verify_latency" in r

    def test_stop_without_start_errors(self, node):
        r = call(node, "profile", action="stop")
        assert r.get("error"), r


class TestRemainingHandlers:
    """Behavioral coverage for the handlers no other test exercised
    directly (presence was judge-verified; these pin behavior)."""

    def test_random(self, node):
        r1 = call(node, "random")
        r2 = call(node, "random")
        assert len(bytes.fromhex(r1["random"])) == 32
        assert r1["random"] != r2["random"]

    def test_validation_create_deterministic_from_secret(self, node):
        a = call(node, "validation_create", secret="hello world")
        b = call(node, "validation_create", secret="hello world")
        assert a["validation_public_key"] == b["validation_public_key"]
        assert a["validation_seed"] == b["validation_seed"]
        c = call(node, "validation_create")
        assert c["validation_public_key"] != a["validation_public_key"]

    def test_validation_seed_non_validator(self, node):
        r = call(node, "validation_seed")
        assert r.get("message") == "not a validator" or (
            "validation_public_key" in r
        )

    def test_consensus_info_standalone(self, node):
        r = call(node, "consensus_info")["info"]
        assert r["standalone"] is True
        assert "validation_quorum" in r

    def test_log_level_roundtrip(self, node):
        import logging

        base = logging.getLogger("stellard")
        dev = logging.getLogger("stellard.device")
        before = (base.level, dev.level)
        try:
            call(node, "log_level", severity="warn")
            assert base.level == logging.WARNING
            call(node, "log_level", severity="debug", partition="device")
            assert dev.level == logging.DEBUG
            r = call(node, "log_level", severity="debug",
                     partition="devcie")
            assert r.get("error") == "invalidParams"
            r = call(node, "log_level")
            assert r["levels"]["base"] == "warning"
            assert r["levels"]["device"] == "debug"
            r = call(node, "log_level", severity="nonsense")
            assert r.get("error") == "invalidParams"
        finally:
            base.setLevel(before[0])
            dev.setLevel(before[1])

    def test_feature_shape(self, node):
        assert call(node, "feature") == {"features": {}}

    def test_tx_history_lists_committed(self, node):
        r = call(node, "tx_history")
        assert r["index"] == 0
        assert len(r["txs"]) >= 2  # the fixture's setup payments
        assert all("hash" in t and "ledger_index" in t for t in r["txs"])

    def test_account_offers_lists_alice(self, node):
        r = call(node, "account_offers", account=ALICE.human_account_id)
        assert len(r["offers"]) == 1
        off = r["offers"][0]
        assert off["taker_gets"] == str(10 * XRP)
        assert off["taker_pays"]["currency"] == "USD"

    def test_account_offers_unknown_account(self, node):
        ghost = KeyPair.from_passphrase("rpc-ghost")
        r = call(node, "account_offers", account=ghost.human_account_id)
        assert r.get("error") == "actNotFound"

    def test_book_offers_renders_alice_offer(self, node):
        # native currency on this chain is "STR" (the reference's
        # SYSTEM_CURRENCY_CODE) — "XRP" would pack as a REAL 3-letter
        # code and address a different (empty) book
        r = call(
            node, "book_offers",
            taker_pays={"currency": "USD",
                        "issuer": node.master_keys.human_account_id},
            taker_gets={"currency": "STR"},
        )
        assert len(r["offers"]) == 1
        assert r["offers"][0]["Account"] == ALICE.human_account_id

    def test_ripple_path_find_direct(self, node):
        r = call(
            node, "ripple_path_find",
            source_account=node.master_keys.human_account_id,
            destination_account=ALICE.human_account_id,
            destination_amount=str(5 * XRP),
        )
        assert "alternatives" in r

    def test_account_tx_switch_routes_old_and_new(self, node):
        new = call(node, "account_tx_switch",
                   account=ALICE.human_account_id, limit=5)
        old = call(node, "account_tx_switch",
                   account=ALICE.human_account_id, ledger_min=-1,
                   ledger_max=-1)
        assert "transactions" in new and "transactions" in old

    def test_unl_load_reseeds_from_config(self, node):
        r = call(node, "unl_load")
        assert not r.get("error"), r

    def test_inflate_requires_seq(self, node):
        r = call(node, "inflate")
        assert r.get("error") == "invalidParams"

    def test_unsubscribe_requires_ws(self, node):
        r = call(node, "unsubscribe", streams=["ledger"])
        assert r.get("error") == "notSupported"
