"""Scenario-search plane (stellard_tpu/testkit/search.py): generator /
coverage / shrinker determinism, schedule+scenario serialization round
trips, the planted-bug shrink fixture, the minimal-repro corpus, and
unit pins for the real bugs the first sweep found (PR 12)."""

from __future__ import annotations

import json
import os
import subprocess
import sys

import pytest

from stellard_tpu.testkit.scenario import (
    SYNTH_BUG,
    Scenario,
    run_simnet,
)
from stellard_tpu.testkit.scenarios import (
    MATRIX,
    build_scenario,
    load_corpus,
)
from stellard_tpu.testkit.schedule import FaultSchedule
from stellard_tpu.testkit.search import (
    SYNTH_THRESHOLD,
    ScenarioGenerator,
    Violation,
    check_invariants,
    coverage_signature,
    schedule_groups,
    shrink_scenario,
    sweep,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- serialization round trips (digest-pinned) ----------------------------

class TestScheduleSerialization:
    def test_round_trip_digest(self):
        sched = FaultSchedule(9)
        sched.partition(10, {0, 1}, {2, 3}, heal_at=20)
        sched.kill(12, 2, revive_at=18)
        sched.link_fault(5, 0, 3, until=15, drop=0.3, dup=0.1,
                         jitter_steps=2)
        sched.add(7, "synth_plant", 2)
        rt = FaultSchedule.from_json(
            json.loads(json.dumps(sched.to_json()))
        )
        assert rt.digest() == sched.digest()
        assert rt.describe() == sched.describe()

    def test_round_trip_preserves_edit_order(self):
        sched = FaultSchedule(0)
        sched.kill(30, 1, revive_at=35)
        sched.kill(10, 2, revive_at=14)
        rt = FaultSchedule.from_json(sched.to_json())
        # a later add() keeps numbering after the round trip
        rt.add(50, "kill", 3)
        assert rt.events[-1].order == 4

    def test_groups_pair_openers_with_closers(self):
        sched = FaultSchedule(0)
        sched.partition(10, {0}, {1, 2}, heal_at=20)
        sched.kill(12, 2, revive_at=18)
        sched.link_fault(5, 0, 1, until=15, drop=0.3)
        sched.add(7, "synth_plant", 1)
        groups = schedule_groups(sched)
        assert len(groups) == 4
        sizes = sorted(len(g) for g in groups)
        assert sizes == [1, 2, 2, 2]


class TestScenarioSerialization:
    def test_matrix_round_trips_digest_pinned(self):
        for name in MATRIX:
            scn = build_scenario(name, seed=7)
            rt = Scenario.from_json(
                json.loads(json.dumps(scn.to_json()))
            )
            assert rt.digest() == scn.digest(), name
            if scn.schedule is not None:
                assert rt.schedule.digest() == scn.schedule.digest()

    def test_closure_builders_refuse_to_serialize(self):
        scn = Scenario(name="x", build_workload=lambda *a: [])
        with pytest.raises(ValueError):
            scn.to_json()


# -- generator determinism ------------------------------------------------

class TestGeneratorDeterminism:
    def test_same_seed_same_scenarios(self):
        a = ScenarioGenerator(11)
        b = ScenarioGenerator(11)
        for _ in range(6):
            assert a.fresh().digest() == b.fresh().digest()

    def test_mutation_stream_deterministic(self):
        a = ScenarioGenerator(5)
        b = ScenarioGenerator(5)
        pa, pb = a.fresh(), b.fresh()
        for _ in range(4):
            ma, mb = a.mutate(pa), b.mutate(pb)
            assert ma.digest() == mb.digest()

    def test_validity_constraints(self):
        gen = ScenarioGenerator(3)
        for _ in range(40):
            scn = gen.fresh()
            # safety: quorum is a majority of the FULL validator count
            assert scn.quorum > scn.n_validators // 2
            if scn.byzantine:
                assert scn.quorum > (scn.n_validators + 1) // 2
                assert scn.quorum <= scn.n_validators - 1
            # liveness: every kill revives, every partition heals,
            # every link fault clears
            opens = {"kill": 0, "partition": 0, "link_fault": 0}
            closes = {"revive": 0, "heal": 0, "clear_link_fault": 0}
            for e in scn.schedule.events:
                if e.kind in opens:
                    opens[e.kind] += 1
                if e.kind in closes:
                    closes[e.kind] += 1
            assert opens["kill"] == closes["revive"]
            assert opens["partition"] == closes["heal"]
            assert opens["link_fault"] == closes["clear_link_fault"]
            # cold nodes are never killed by the schedule
            for e in scn.schedule.events:
                if e.kind == "kill":
                    assert e.args[0] not in scn.cold_nodes


_XPROC_DRIVER = r"""
import json, sys
sys.path.insert(0, @@REPO@@)
from stellard_tpu.testkit.search import (
    ScenarioGenerator, Violation, shrink_scenario, sweep,
)
from stellard_tpu.testkit.scenario import SYNTH_BUG, Scenario
from stellard_tpu.testkit.schedule import FaultSchedule

# (a) generated scenario digests, no runs
gen = ScenarioGenerator(13, allow_synth=True)
digests = [gen.fresh().digest() for _ in range(8)]
# (b) a tiny real sweep: schedule sequence + coverage trajectory
res = sweep(13, 3, shrink=False, determinism_check=False)
# (c) the planted-bug shrink trajectory
sched = FaultSchedule(1)
sched.add(8, "synth_plant", 2)
sched.kill(10, 1, revive_at=14)
sched.add(20, "synth_plant", 2)
scn = Scenario(name="fixture", seed=1, n_validators=4, quorum=3,
               steps=34, schedule=sched,
               workload={"kind": "payment_flood", "n": 10})
SYNTH_BUG["armed"] = True
minimal, traj = shrink_scenario(
    scn, Violation("synthetic_bug", ""), max_runs=40
)
SYNTH_BUG["armed"] = False
print(json.dumps({
    "digests": digests,
    "schedule_digests": res["scenario_digests"],
    "coverage": res["coverage_trajectory"],
    "shrink": [(t["op"], t["kept"], t["digest"]) for t in traj],
    "minimal": minimal.digest(),
}, sort_keys=True))
"""


@pytest.mark.slow
class TestCrossProcessDeterminism:
    def test_hashseed_invariance(self):
        """Same fuzz seed -> byte-identical generated schedule
        sequence, coverage map trajectory, and shrink trajectory
        across processes with DIFFERENT PYTHONHASHSEED (the
        FoundationDB property, extended to the search plane)."""
        outs = []
        for hashseed in ("1", "31337"):
            env = dict(os.environ)
            env["PYTHONHASHSEED"] = hashseed
            env["JAX_PLATFORMS"] = "cpu"
            r = subprocess.run(
                [sys.executable, "-c",
                 _XPROC_DRIVER.replace("@@REPO@@", repr(REPO))],
                capture_output=True, text=True, timeout=600, env=env,
                cwd=REPO,
            )
            assert r.returncode == 0, r.stderr[-2000:]
            outs.append(r.stdout.strip().splitlines()[-1])
        assert outs[0] == outs[1]


# -- invariants (cheap, synthetic scorecards) -----------------------------

def _base_card(**over):
    card = {
        "converged": True, "single_hash": True, "fork_seqs": [],
        "submitted": 10, "committed": 10, "validated_seqs": [5, 5],
        "net": {"sent": 100, "dropped_down": 1, "dropped_link": 1},
        "tail_steps": 10, "final_seq": 5, "degraded_transitions": 0,
    }
    card.update(over)
    return card


class TestInvariants:
    def test_clean_card_clean(self):
        scn = Scenario(name="x")
        assert check_invariants(scn, _base_card()) == []

    def test_synthetic_threshold(self):
        scn = Scenario(name="x")
        card = _base_card(synth={"planted": SYNTH_THRESHOLD})
        got = check_invariants(scn, card)
        assert got and got[0].invariant == "synthetic_bug"
        card = _base_card(synth={"planted": SYNTH_THRESHOLD - 1})
        assert check_invariants(scn, card) == []

    def test_determinism_rerun_compared(self):
        scn = Scenario(name="x")
        a = _base_card()
        b = _base_card(final_seq=6)
        got = check_invariants(scn, a, b)
        assert any(v.invariant == "determinism" for v in got)
        # the wall-clock spec block is excluded by design
        a2 = _base_card(spec={"dispatched": 5})
        b2 = _base_card(spec={"dispatched": 9})
        assert check_invariants(scn, a2, b2) == []

    def test_committed_floor_and_fork(self):
        scn = Scenario(name="x")
        got = check_invariants(scn, _base_card(committed=8))
        assert any(v.invariant == "committed_floor" for v in got)
        got = check_invariants(scn, _base_card(fork_seqs=[3]))
        assert any(
            v.invariant == "single_hash_history" for v in got
        )

    def test_anti_vacuity(self):
        sched = FaultSchedule(0)
        sched.kill(5, 1, revive_at=9)
        scn = Scenario(name="x", schedule=sched)
        card = _base_card(net={"sent": 50, "dropped_down": 0})
        got = check_invariants(scn, card)
        assert any(v.invariant == "anti_vacuity" for v in got)
        # link fault: exposure is the evidence, not drop luck
        sched2 = FaultSchedule(0)
        sched2.link_fault(5, 0, 1, until=12, drop=0.3)
        scn2 = Scenario(name="y", schedule=sched2)
        card = _base_card(net={
            "sent": 50, "dropped_fault": 0, "fault_exposed": 12,
        })
        assert check_invariants(scn2, card) == []
        card = _base_card(net={"sent": 50, "fault_exposed": 0})
        got = check_invariants(scn2, card)
        assert any(v.invariant == "anti_vacuity" for v in got)

    def test_txq_verdicts_replace_commit_floor(self):
        scn = Scenario(name="x", txq_cap=5)
        card = _base_card(
            committed=6,
            txq={"no_starvation": True, "fee_order_drain": True},
        )
        assert check_invariants(scn, card) == []
        card = _base_card(
            txq={"no_starvation": False, "fee_order_drain": True},
        )
        got = check_invariants(scn, card)
        assert any(v.invariant == "txq_no_starvation" for v in got)


# -- sweep mechanics (stubbed run_fn — no simulation) ---------------------

class TestSweepMechanics:
    def _run_fn(self, fail_iter=()):
        calls = {"n": 0}

        def run(scn):
            i = calls["n"]
            calls["n"] += 1
            card = _base_card()
            card["net"] = {"sent": 10 * (1 + i % 3)}
            if calls["n"] - 1 in fail_iter:
                card["committed"] = 0
            return card

        return run

    def test_coverage_map_and_trajectory(self):
        res = sweep(1, 6, shrink=False, determinism_check=False,
                    run_fn=self._run_fn())
        assert res["runs"] == 6
        assert len(res["coverage_trajectory"]) == 6
        assert len(res["scenario_digests"]) == 6
        assert res["distinct_signatures"] >= 1

    def test_shrink_budget_one_per_invariant(self):
        # every run violates committed_floor; only the FIRST violation
        # gets the full shrink, later ones are recorded raw
        res = sweep(1, 4, shrink=True, determinism_check=False,
                    run_fn=self._run_fn(fail_iter=range(99)),
                    max_shrink_runs=6)
        floors = [v for v in res["violations"]
                  if v["invariant"] == "committed_floor"]
        assert len(floors) == 4  # one record per run
        shrunk = [v for v in floors if "shrunk" in v]
        assert len(shrunk) == 1  # but only the FIRST carries a shrink
        assert shrunk[0]["entry"]["invariant"] == "committed_floor"
        # a co-occurring violation of another class is ALSO recorded
        # (synthetic_bug first-ordering must not mask real findings)
        per_run = {}
        for v in res["violations"]:
            per_run.setdefault(v["iteration"], set()).add(v["invariant"])
        assert any(len(kinds) > 1 for kinds in per_run.values())


# -- the planted-bug shrink fixture ---------------------------------------

class TestShrinker:
    def _fixture(self):
        sched = FaultSchedule(1)
        sched.add(8, "synth_plant", 2)
        sched.kill(10, 1, revive_at=14)
        sched.partition(16, (0, 1), (2, 3))
        sched.add(24, "heal", (0, 1), (2, 3))
        sched.add(20, "synth_plant", 2)
        return Scenario(
            name="fixture", seed=1, n_validators=4, quorum=3,
            steps=34, schedule=sched,
            workload={"kind": "payment_flood", "n": 10},
        )

    def test_converges_to_known_minimum(self):
        scn = self._fixture()
        SYNTH_BUG["armed"] = True
        try:
            minimal, traj = shrink_scenario(
                scn, Violation("synthetic_bug", ""), max_runs=50
            )
        finally:
            SYNTH_BUG["armed"] = False
        events = minimal.schedule.events
        kinds = {e.kind for e in events}
        assert kinds == {"synth_plant"}
        assert len(events) == 2
        total = sum(e.args[0] for e in events)
        assert total == SYNTH_THRESHOLD
        assert minimal.workload is None
        assert traj  # trajectory recorded

    def test_trajectory_deterministic(self):
        scn = self._fixture()
        SYNTH_BUG["armed"] = True
        try:
            _m1, t1 = shrink_scenario(
                scn, Violation("synthetic_bug", ""), max_runs=50
            )
            _m2, t2 = shrink_scenario(
                self._fixture(), Violation("synthetic_bug", ""),
                max_runs=50,
            )
        finally:
            SYNTH_BUG["armed"] = False
        assert t1 == t2


# -- the permanent corpus -------------------------------------------------

class TestCorpus:
    def test_entries_load_through_build_scenario(self):
        corpus = load_corpus()
        assert len(corpus) >= 5  # the PR 12 first-sweep finds
        for name, entry in corpus.items():
            scn = build_scenario(name)
            assert scn.digest() == Scenario.from_json(
                entry["scenario"]
            ).digest()
            assert entry["expect"] == "pass"
            assert entry["invariant"]

    def test_catchup_limit_cycle_regression(self):
        """The headline first-sweep find: an even partition healing
        under quorum 5-of-6 plus one kill wedged the whole net at
        validated seq 3 FOREVER (stragglers tracked the tip at a
        constant offset; no seq could re-assemble quorum). Pinned by
        replaying its shrunk corpus entry clean."""
        name = next(
            n for n in load_corpus() if n.startswith("fuzz_convergence")
        )
        scn = build_scenario(name)
        card = run_simnet(scn)
        assert check_invariants(scn, card) == []
        assert card["converged"] and card["single_hash"]


# -- unit pins for the fixed product bugs ---------------------------------

class TestValidationMonotonicity:
    def test_can_sign_strictly_increasing(self):
        from stellard_tpu.consensus.validation import STValidation
        from stellard_tpu.consensus.validations import ValidationsStore
        from stellard_tpu.protocol.keys import KeyPair

        key = KeyPair.from_passphrase("monotonic-test")
        store = ValidationsStore(
            is_trusted=lambda pub: True, now=lambda: 1000
        )
        assert store.can_sign(5)
        val = STValidation.build(
            b"\x01" * 32, signing_time=1000, ledger_seq=5
        )
        val.sign(key)
        store.add(val, local=True)
        # fork repair must never sign a SECOND statement at seq <= 5
        assert not store.can_sign(5)
        assert not store.can_sign(4)
        assert store.can_sign(6)


class TestProposalPlayback:
    def test_stashed_proposal_replays_into_new_round(self):
        """playbackProposals: a proposal for a round we had not begun
        yet must be replayed once begin_round reaches its prior ledger
        (without it, late round joiners closed solo ledgers — the
        catch-up limit cycle)."""
        from stellard_tpu.overlay.simnet import SimNet

        net = SimNet(4, quorum=3, seed=3)
        net.start()
        net.step(8)
        v0 = net.validators[0].node
        assert v0._recent_proposals  # trusted positions stashed
        # every stashed position for the CURRENT round's prev is
        # already reflected in the round's peer_positions via playback
        # or direct delivery
        rnd = v0.round
        assert rnd is not None
        for pub in v0._recent_proposals:
            for _when, prop in v0._recent_proposals[pub]:
                if prop.prev_ledger == rnd.prev_hash:
                    assert pub in rnd.peer_positions or \
                        rnd.max_seen_seq.get(pub, -1) >= prop.propose_seq


class TestInboundClock:
    def test_expiry_on_injected_clock(self):
        from stellard_tpu.node.inbound import InboundLedgers

        t = [0.0]
        inb = InboundLedgers(send=lambda req: None, clock=lambda: t[0])
        inb.acquire(b"\x07" * 32)
        assert inb.expire_stale(max_age_s=30.0) == 0
        t[0] = 31.0
        assert inb.expire_stale(max_age_s=30.0) == 1
        assert b"\x07" * 32 not in inb.live
        assert inb.recently_done(b"\x07" * 32)

    def test_fetch_pack_serves_deep_paths(self):
        """DFS fetch packs: a chain of single-child inners (order-book
        directories share 24-byte key prefixes) must serve in ONE
        reply, not one level per round trip."""
        from stellard_tpu.node.inbound import (
            W_STATE_TREE,
            serve_get_ledger,
        )
        from stellard_tpu.overlay.wire import GetLedger
        from stellard_tpu.state.ledger import Ledger
        from stellard_tpu.state.shamap import SHAMapItem

        led = Ledger.genesis(b"\x11" * 20)
        # two entries sharing a 24-byte prefix -> ~48-nibble chain path
        base = b"\xab" * 24
        led.state_map.set_item(SHAMapItem(
            base + b"\x01" * 8, b"leaf-one"
        ))
        led.state_map.set_item(SHAMapItem(
            base + b"\x02" * 8, b"leaf-two"
        ))
        led.state_map.get_hash()
        reply = serve_get_ledger(
            led, GetLedger(led.hash(), 0, W_STATE_TREE, [])
        )
        # whole path in one reply: both leaves present
        blobs = b"".join(b for _nid, b in reply.nodes)
        assert b"leaf-one" in blobs and b"leaf-two" in blobs

    def test_push_closed_never_clobbers_validated_slot(self):
        from stellard_tpu.node.ledgermaster import LedgerMaster
        from stellard_tpu.state.ledger import Ledger

        lm = LedgerMaster()
        lm.start_new_ledger(b"\x11" * 20)
        led = lm.closed_ledger()
        lm.set_validated(led)
        canonical = lm.ledger_history[led.seq]
        # a stale churned round closes ANOTHER ledger at the same seq
        orphan = Ledger.genesis(b"\x22" * 20)
        orphan.seq = led.seq
        lm._push_closed(orphan)
        assert lm.ledger_history[led.seq] == canonical
        # above the floor the fresh close indexes normally
        orphan2 = Ledger.genesis(b"\x33" * 20)
        orphan2.seq = led.seq + 1
        lm._push_closed(orphan2)
        assert lm.ledger_history[led.seq + 1] == orphan2.hash()


class TestNewMatrixVariants:
    def test_follower_partition_syncs(self):
        card = run_simnet(build_scenario("follower_partition", seed=7))
        assert card["converged"] and card["single_hash"]
        assert card["followers"]["synced"]
        assert card["net"]["dropped_link"] > 0  # partition was real
        assert card["committed"] == card["submitted"]

    def test_squelch_rotation_flood_defends(self):
        scn = build_scenario("squelch_rotation_flood", seed=7)
        card = run_simnet(scn)
        assert card["converged"] and card["single_hash"]
        assert card["committed"] == card["submitted"]
        # rotation happened AND the fan-out bound held across epochs
        assert card["relay"]["relay_fanout_max"] <= (
            scn.squelch_size + scn.n_validators
        )
        fl = next(iter(card["flooders"].values()))
        assert fl["refused_by"] > 0

    def test_chaos_spec2_buildable_and_serializable(self):
        scn = build_scenario("chaos_spec2", seed=7)
        assert scn.spec_workers == 2
        assert Scenario.from_json(scn.to_json()).digest() == scn.digest()


class TestCoverageSignature:
    def test_signature_stable_and_config_blind(self):
        a = _base_card()
        assert coverage_signature(a) == coverage_signature(dict(a))
        # pure traffic-volume change: same dynamics state
        b = _base_card(net={"sent": 900, "dropped_down": 2,
                            "dropped_link": 3})
        assert coverage_signature(a) == coverage_signature(b)
        # a machinery change IS a new state
        c = _base_card(byzantine={"malformed_frame": 4})
        assert coverage_signature(a) != coverage_signature(c)
