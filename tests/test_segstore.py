"""Segmented log-structured NodeStore (nodestore/segstore.py) + the
storage-plane satellites: one-append packed flush, durability modes,
checkpointed open (tail-only replay, pinned record counts), torn-tail
crash recovery, online deletion (mark-and-sweep) with compaction and
the disk-bounded invariant, the segment-granular read door, cpplog
iteration, and sqlite WAL hygiene."""

from __future__ import annotations

import hashlib
import os
import struct

import pytest

from stellard_tpu.nodestore import (
    NodeObject,
    NodeObjectType,
    SegStoreBackend,
    make_database,
)
from stellard_tpu.utils.hashes import sha512_half


def _blobs(n, tag="n", size=40):
    """Content-addressed test corpus: prefix-format-looking blobs keyed
    by their real sha512-half (fetch_segment verification depends on
    blob == hashed bytes)."""
    out = []
    for i in range(n):
        blob = b"MIN" + hashlib.sha256(f"{tag}:{i}".encode()).digest() * (
            max(1, size // 32)
        )
        out.append((sha512_half(blob), blob))
    return out


def _flat(pairs):
    buf = bytearray()
    offsets = [0]
    keys = []
    for k, b in pairs:
        keys.append(k)
        buf += b
        offsets.append(len(buf))
    return keys, bytes(buf), offsets


def _store_packed(db, pairs, type=NodeObjectType.ACCOUNT_NODE):
    keys, buf, offsets = _flat(pairs)
    return db.store_packed(type, keys, buf, offsets)


NATIVE_MODES = [False]
try:
    from stellard_tpu.native import load_native

    _lib = load_native()
    if _lib is not None and getattr(_lib, "has_segstore", False):
        NATIVE_MODES.append(True)
except Exception:  # noqa: BLE001
    pass


@pytest.fixture(params=NATIVE_MODES, ids=lambda p: "native" if p else "py")
def use_native(request):
    return request.param


class TestSegStoreBasics:
    def test_packed_roundtrip_and_dedup(self, tmp_path, use_native):
        db = make_database(type="segstore", path=str(tmp_path / "ns"),
                           use_native=use_native)
        pairs = _blobs(300)
        assert _store_packed(db, pairs) == 300
        # content-addressed: a second flush of the same nodes is a no-op
        assert _store_packed(db, pairs) == 0
        for k, b in pairs:
            obj = db.fetch(k)
            assert obj.data == b
            assert obj.type == NodeObjectType.ACCOUNT_NODE
        assert db.fetch(b"\x00" * 32) is None
        assert db.backend.count() == 300
        db.close()

    def test_store_batch_matches_packed(self, tmp_path, use_native):
        """The NodeObject batch door and the flat-buffer door must
        produce byte-identical stores."""
        pairs = _blobs(64)
        db_a = make_database(type="segstore", path=str(tmp_path / "a"),
                             use_native=use_native)
        _store_packed(db_a, pairs)
        db_b = make_database(type="segstore", path=str(tmp_path / "b"),
                             use_native=use_native)
        db_b.backend.store_batch([
            NodeObject(NodeObjectType.ACCOUNT_NODE, k, b) for k, b in pairs
        ])
        for k, b in pairs:
            assert db_a.fetch(k).data == db_b.fetch(k).data == b
        sa = sorted((o.hash, o.data) for o in db_a.backend.iterate())
        sb = sorted((o.hash, o.data) for o in db_b.backend.iterate())
        assert sa == sb
        db_a.close()
        db_b.close()

    def test_in_batch_duplicates_collapse(self, tmp_path, use_native):
        db = make_database(type="segstore", path=str(tmp_path / "ns"),
                           use_native=use_native)
        pairs = _blobs(8)
        doubled = pairs + pairs
        assert _store_packed(db, doubled) == 8
        assert db.backend.count() == 8
        db.close()

    def test_segment_roll_and_fetch_across(self, tmp_path, use_native):
        db = make_database(type="segstore", path=str(tmp_path / "ns"),
                           segment_bytes=1 << 16, use_native=use_native)
        pairs = _blobs(2000, size=64)
        for start in range(0, 2000, 100):
            _store_packed(db, pairs[start:start + 100])
        segs = db.backend.segments()
        assert len(segs) > 1  # rolled at least once
        assert sum(1 for s in segs if s["active"]) == 1
        for k, b in pairs:
            assert db.fetch(k).data == b
        db.close()

    def test_native_py_file_format_parity(self, tmp_path):
        """A store written by the pure-Python paths opens and reads
        under the native paths, and vice versa — one on-disk format."""
        if True not in NATIVE_MODES:
            pytest.skip("native toolchain unavailable")
        pairs = _blobs(200)
        db = make_database(type="segstore", path=str(tmp_path / "ns"),
                           use_native=False)
        _store_packed(db, pairs)
        db.close()
        db2 = make_database(type="segstore", path=str(tmp_path / "ns"),
                            use_native=True)
        assert db2.backend.count() == 200
        for k, b in pairs:
            assert db2.fetch(k).data == b
        more = _blobs(50, tag="native-side")
        _store_packed(db2, more)
        db2.close()
        db3 = make_database(type="segstore", path=str(tmp_path / "ns"),
                            use_native=False)
        assert db3.backend.count() == 250
        for k, b in pairs + more:
            assert db3.fetch(k).data == b
        db3.close()

    def test_bad_durability_rejected(self, tmp_path):
        with pytest.raises(ValueError):
            SegStoreBackend(str(tmp_path / "ns"), durability="yolo")


class TestDurabilityModes:
    def test_fsync_per_batch(self, tmp_path, use_native):
        db = make_database(type="segstore", path=str(tmp_path / "ns"),
                           durability="fsync", use_native=use_native)
        for chunk in range(4):
            _store_packed(db, _blobs(10, tag=f"c{chunk}"))
        be = db.backend
        assert be.appends == 4
        assert be.fsyncs >= 4  # one per batch (rolls/checkpoints add)
        db.close()

    def test_batch_group_commit_shares_fsyncs(self, tmp_path, use_native):
        db = make_database(type="segstore", path=str(tmp_path / "ns"),
                           durability="batch", group_commit_ms=10_000.0,
                           use_native=use_native)
        for chunk in range(8):
            _store_packed(db, _blobs(10, tag=f"c{chunk}"))
        be = db.backend
        assert be.appends == 8
        assert be.fsyncs == 0  # window far in the future: all deferred
        db.sync()  # the explicit durability barrier forces one
        assert be.fsyncs == 1
        db.close()

    def test_async_defers_to_sync(self, tmp_path, use_native):
        db = make_database(type="segstore", path=str(tmp_path / "ns"),
                           durability="async", use_native=use_native)
        _store_packed(db, _blobs(10))
        assert db.backend.fsyncs == 0
        db.sync()
        assert db.backend.fsyncs == 1
        db.close()


class TestCheckpointedOpen:
    def test_clean_close_reopens_with_zero_replay(self, tmp_path,
                                                  use_native):
        db = make_database(type="segstore", path=str(tmp_path / "ns"),
                           use_native=use_native)
        pairs = _blobs(500)
        _store_packed(db, pairs)
        db.close()  # close writes a checkpoint
        db2 = make_database(type="segstore", path=str(tmp_path / "ns"),
                            use_native=use_native)
        be = db2.backend
        assert be.opened_from_checkpoint
        assert be.replayed_records == 0  # the whole point of the ckpt
        assert be.count() == 500
        for k, b in pairs:
            assert db2.fetch(k).data == b
        db2.close()

    def test_tail_only_replay_counts_pinned(self, tmp_path, use_native):
        """Records appended after the last checkpoint — and ONLY those —
        replay on open."""
        db = make_database(type="segstore", path=str(tmp_path / "ns"),
                           use_native=use_native)
        _store_packed(db, _blobs(300, tag="covered"))
        db.backend.checkpoint()
        tail = _blobs(37, tag="tail")
        _store_packed(db, tail)
        # crash: no close(), no final checkpoint
        db.backend._active_f.flush()
        db2 = make_database(type="segstore", path=str(tmp_path / "ns"),
                            use_native=use_native)
        be = db2.backend
        assert be.opened_from_checkpoint
        assert be.replayed_records == 37  # the tail, nothing else
        assert be.count() == 337
        for k, b in tail:
            assert db2.fetch(k).data == b
        db2.close()

    def test_corrupt_checkpoint_degrades_to_full_replay(self, tmp_path,
                                                        use_native):
        db = make_database(type="segstore", path=str(tmp_path / "ns"),
                           use_native=use_native)
        pairs = _blobs(120)
        _store_packed(db, pairs)
        db.close()
        ckpt = tmp_path / "ns" / "index.ckpt"
        blob = bytearray(ckpt.read_bytes())
        blob[len(blob) // 2] ^= 0xFF  # flip a byte: crc must catch it
        ckpt.write_bytes(bytes(blob))
        db2 = make_database(type="segstore", path=str(tmp_path / "ns"),
                            use_native=use_native)
        be = db2.backend
        assert not be.opened_from_checkpoint
        assert be.replayed_records == 120  # full scan
        for k, b in pairs:
            assert db2.fetch(k).data == b
        db2.close()

    def test_checkpoint_referencing_missing_segment_discarded(
            self, tmp_path, use_native):
        db = make_database(type="segstore", path=str(tmp_path / "ns"),
                           segment_bytes=1 << 16, use_native=use_native)
        pairs = _blobs(1500, size=64)
        for start in range(0, 1500, 100):
            _store_packed(db, pairs[start:start + 100])
        db.close()
        segs = sorted(
            p for p in os.listdir(tmp_path / "ns") if p.endswith(".seg")
        )
        assert len(segs) > 1
        os.remove(tmp_path / "ns" / segs[0])
        db2 = make_database(type="segstore", path=str(tmp_path / "ns"),
                            use_native=use_native)
        # degraded to a full replay of what remains, not stale index
        # entries pointing at a missing file
        assert not db2.backend.opened_from_checkpoint
        resolvable = sum(1 for k, _ in pairs if db2.fetch(k) is not None)
        assert 0 < resolvable < 1500
        db2.close()


class TestTornTailRecovery:
    def test_torn_tail_truncated_on_reopen(self, tmp_path, use_native):
        db = make_database(type="segstore", path=str(tmp_path / "ns"),
                           use_native=use_native)
        _store_packed(db, _blobs(50, tag="pre-ckpt"))
        db.backend.checkpoint()
        survivors = _blobs(20, tag="post")
        _store_packed(db, survivors)
        db.backend._active_f.flush()
        seg = sorted(
            p for p in os.listdir(tmp_path / "ns") if p.endswith(".seg")
        )[-1]
        path = tmp_path / "ns" / seg
        clean = path.stat().st_size
        # simulated kill mid-append: a header claiming more bytes than
        # exist, plus partial body
        with open(path, "ab") as f:
            f.write(struct.pack("<IB", 500, 0) + b"\xAA" * 40)
        db2 = make_database(type="segstore", path=str(tmp_path / "ns"),
                            use_native=use_native)
        assert path.stat().st_size == clean  # torn record truncated away
        assert db2.backend.replayed_records == 20
        for k, b in survivors:
            assert db2.fetch(k).data == b
        # appends after recovery land on the clean boundary and resolve
        more = _blobs(10, tag="after-recovery")
        _store_packed(db2, more)
        db2.close()
        db3 = make_database(type="segstore", path=str(tmp_path / "ns"),
                            use_native=use_native)
        for k, b in survivors + more:
            assert db3.fetch(k).data == b
        db3.close()

    def test_cpplog_torn_tail_still_recovers(self, tmp_path):
        """cpplog keeps its own torn-tail truncation (test_native pins
        the fine detail); this pins the shared crash-recovery contract
        both durable backends honor: reopen after a torn append resolves
        every previously-synced record."""
        try:
            db = make_database(type="cpplog",
                               path=str(tmp_path / "ns.cpplog"))
        except (RuntimeError, OSError):
            pytest.skip("native toolchain unavailable")
        pairs = _blobs(30)
        db.backend.store_batch([
            NodeObject(NodeObjectType.ACCOUNT_NODE, k, b) for k, b in pairs
        ])
        db.close()
        with open(tmp_path / "ns.cpplog", "ab") as f:
            f.write(struct.pack("<IB", 999, 0) + b"\xBB" * 21)
        db2 = make_database(type="cpplog", path=str(tmp_path / "ns.cpplog"))
        for k, b in pairs:
            assert db2.fetch(k).data == b
        db2.close()


class TestOnlineDeletion:
    def test_sweep_removes_only_dead(self, tmp_path, use_native):
        db = make_database(type="segstore", path=str(tmp_path / "ns"),
                           use_native=use_native)
        keep = _blobs(40, tag="keep")
        dead = _blobs(60, tag="dead")
        _store_packed(db, keep + dead)
        db.begin_sweep()
        removed = db.apply_sweep({k for k, _ in keep})
        assert removed == 60
        for k, b in keep:
            assert db.fetch(k).data == b
        for k, _ in dead:
            assert db.fetch(k) is None
        db.close()

    def test_sweep_purges_flushed_known_set(self, tmp_path, use_native):
        """The façade's `flushed` set must forget swept keys, or a later
        flush would skip re-writing a node a new ledger re-created."""
        db = make_database(type="segstore", path=str(tmp_path / "ns"),
                           use_native=use_native)
        pairs = _blobs(10)
        _store_packed(db, pairs)
        db.flushed.update(k for k, _ in pairs)
        db.begin_sweep()
        db.apply_sweep(set())
        assert not (db.flushed & {k for k, _ in pairs})
        # re-stored after the sweep: resolvable again
        assert _store_packed(db, pairs) == 10
        for k, b in pairs:
            assert db.fetch(k).data == b
        db.close()

    def test_mid_sweep_append_survives(self, tmp_path, use_native):
        """A key appended between begin_sweep and apply_sweep must
        survive even when the mark never saw it (recent-key guard +
        compare-and-delete)."""
        db = make_database(type="segstore", path=str(tmp_path / "ns"),
                           use_native=use_native)
        old = _blobs(20, tag="old")
        _store_packed(db, old)
        db.begin_sweep()
        racing = _blobs(5, tag="racing")
        _store_packed(db, racing)
        # re-append of an existing (dead-listed) key mid-sweep: the
        # fresh record's loc differs from the sweep snapshot's
        _store_packed(db, old[:3])
        removed = db.apply_sweep(set())  # mark saw nothing live
        assert removed == 17  # 20 old minus the 3 re-appended
        for k, b in racing + old[:3]:
            assert db.fetch(k).data == b
        db.close()

    def test_sweep_durable_across_reopen(self, tmp_path, use_native):
        db = make_database(type="segstore", path=str(tmp_path / "ns"),
                           use_native=use_native)
        keep = _blobs(15, tag="keep")
        dead = _blobs(15, tag="dead")
        _store_packed(db, keep + dead)
        db.begin_sweep()
        db.apply_sweep({k for k, _ in keep})
        db.close()
        db2 = make_database(type="segstore", path=str(tmp_path / "ns"),
                            use_native=use_native)
        assert db2.backend.count() == 15
        for k, _ in dead:
            assert db2.fetch(k) is None
        for k, b in keep:
            assert db2.fetch(k).data == b
        db2.close()


class TestCompaction:
    def test_live_ratio_triggers_rewrite(self, tmp_path, use_native):
        db = make_database(type="segstore", path=str(tmp_path / "ns"),
                           segment_bytes=1 << 14, compact_ratio=0.5,
                           use_native=use_native)
        keep = _blobs(30, tag="keep", size=64)
        dead = _blobs(300, tag="dead", size=64)
        for start in range(0, 300, 30):
            _store_packed(db, dead[start:start + 30])
        _store_packed(db, keep)
        be = db.backend
        segs_before = len(be.segments())
        disk_before = be.disk_bytes()
        db.begin_sweep()
        db.apply_sweep({k for k, _ in keep})
        be.compact()
        assert be.compactions >= 1
        assert be.disk_bytes() < disk_before
        # disk bounded within 2x the live set after compaction
        assert be.disk_bytes() <= 2 * be.live_bytes() + (1 << 12)
        assert len(be.segments()) <= segs_before
        for k, b in keep:
            assert db.fetch(k).data == b
        assert be.count() == 30
        db.close()
        # and the compacted store reopens intact
        db2 = make_database(type="segstore", path=str(tmp_path / "ns"),
                            use_native=use_native)
        for k, b in keep:
            assert db2.fetch(k).data == b
        db2.close()

    def test_compaction_preserves_byte_identity(self, tmp_path,
                                                use_native):
        db = make_database(type="segstore", path=str(tmp_path / "ns"),
                           segment_bytes=1 << 13, use_native=use_native)
        pairs = _blobs(200, size=48)
        for start in range(0, 200, 20):
            _store_packed(db, pairs[start:start + 20])
        db.begin_sweep()
        db.apply_sweep({k for k, _ in pairs[::2]})  # half dead
        db.backend.compact()
        for k, b in pairs[::2]:
            obj = db.fetch(k)
            assert obj.data == b
            assert sha512_half(obj.data) == k  # moved bytes re-verify
        db.close()


class TestSegmentReadDoor:
    def test_fetch_segment_serves_verifiable_ranges(self, tmp_path,
                                                    use_native):
        db = make_database(type="segstore", path=str(tmp_path / "ns"),
                           segment_bytes=1 << 14, use_native=use_native)
        pairs = _blobs(300, size=64)
        for start in range(0, 300, 30):
            _store_packed(db, pairs[start:start + 30])
        be = db.backend
        want = dict(pairs)
        seen = 0
        for meta in be.segments():
            got = be.fetch_segment(meta["id"])
            assert got is not None
            m, raw = got
            assert len(raw) == m["size"]
            # every record in the raw range parses and its blob hashes
            # to its key — a catch-up receiver can verify offline
            off = 0
            while off + 37 <= len(raw):
                body_len = struct.unpack_from("<I", raw, off)[0]
                assert off + 37 + body_len <= len(raw)
                key = raw[off + 5: off + 37]
                blob = raw[off + 38: off + 37 + body_len]
                assert sha512_half(blob) == key
                assert want[key] == blob
                seen += 1
                off += 37 + body_len
        assert seen == 300
        assert be.fetch_segment(999999) is None
        db.close()

    def test_fetch_segment_offset_length_edges(self, tmp_path,
                                               use_native):
        """Chunked-transfer edge cases: zero-length reads, offsets at
        and past the end, and a length spanning the end — meta must
        always carry the FULL size, data exactly the clamped range."""
        db = make_database(type="segstore", path=str(tmp_path / "ns"),
                           use_native=use_native)
        _store_packed(db, _blobs(40, size=64))
        be = db.backend
        sid = be.segments()[0]["id"]
        meta, full = be.fetch_segment(sid)
        size = meta["size"]
        assert size == len(full) > 0
        # zero-length read: empty data, full size in meta
        m, data = be.fetch_segment(sid, offset=0, length=0)
        assert data == b"" and m["size"] == size
        # offset exactly at end: empty, not an error
        m, data = be.fetch_segment(sid, offset=size, length=1 << 20)
        assert data == b"" and m["size"] == size
        # offset PAST the end (a hostile/raced chunk request): empty
        m, data = be.fetch_segment(sid, offset=size + 1000, length=64)
        assert data == b"" and m["size"] == size
        # negative offset clamps to 0
        m, data = be.fetch_segment(sid, offset=-5, length=10)
        assert data == full[:10]
        # length spanning past the end clamps to the tail
        m, data = be.fetch_segment(sid, offset=size - 7, length=1 << 20)
        assert data == full[-7:]
        # chunked reassembly reproduces the segment byte-for-byte
        out = bytearray()
        while len(out) < size:
            _m, chunk = be.fetch_segment(sid, offset=len(out), length=13)
            assert chunk
            out += chunk
        assert bytes(out) == full
        db.close()

    def test_fetch_segment_spanning_seal_boundary(self, tmp_path,
                                                  use_native):
        """A reader paging one segment while appends roll into the NEXT
        must see a stable byte range: sealed segments never change, and
        every record in any chunk still verifies."""
        db = make_database(type="segstore", path=str(tmp_path / "ns"),
                           segment_bytes=1 << 16, use_native=use_native)
        pairs = _blobs(600, size=96)
        for start in range(0, 600, 50):
            _store_packed(db, pairs[start:start + 50])
        be = db.backend
        metas = be.segments()
        assert len(metas) >= 2, "workload must span a seal boundary"
        sealed = [m for m in metas if not m["active"]][0]
        m1, first = be.fetch_segment(sealed["id"])
        # a request whose length crosses the sealed segment's end is
        # clamped at the seal — bytes never bleed into the next segment
        m2, clamped = be.fetch_segment(sealed["id"], offset=0,
                                       length=m1["size"] + 4096)
        assert clamped == first
        # appending more (rolls may happen) never mutates a sealed range
        _store_packed(db, _blobs(100, tag="later", size=96))
        _m, again = be.fetch_segment(sealed["id"])
        assert again == first
        db.close()

    def test_fetch_segment_concurrent_with_compaction(self, tmp_path,
                                                      use_native):
        """Readers chunk-paging a segment while compaction rewrites and
        DELETES it must either get a valid chunk or a clean None (the
        manifest row is gone) — never a torn read or a crash."""
        import threading

        db = make_database(type="segstore", path=str(tmp_path / "ns"),
                           segment_bytes=1 << 16, use_native=use_native)
        pairs = _blobs(800, size=128)
        for start in range(0, 800, 40):
            _store_packed(db, pairs[start:start + 40])
        be = db.backend
        sealed = [m for m in be.segments() if not m["active"]]
        assert sealed
        target = sealed[0]["id"]
        # kill most of the sealed segments' liveness so compaction
        # rewrites them
        live_keys = {k for k, _ in pairs[:40]}
        db.begin_sweep()
        db.apply_sweep(live_keys)
        errors: list[BaseException] = []
        stop = threading.Event()

        def reader():
            try:
                while not stop.is_set():
                    got = be.fetch_segment(target, offset=0, length=512)
                    if got is None:
                        continue  # compacted away: clean miss
                    meta, data = got
                    assert len(data) <= 512
            except BaseException as e:  # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=reader) for _ in range(3)]
        for t in threads:
            t.start()
        try:
            be.compact()
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=10)
        assert not errors, errors
        # every LIVE node still fetches byte-identically post-compaction
        for k, blob in pairs[:40]:
            obj = db.fetch(k)
            assert obj is not None and obj.data == blob
        db.close()


class TestCppLogIterate:
    def test_iterate_returns_every_record(self, tmp_path):
        try:
            db = make_database(type="cpplog",
                               path=str(tmp_path / "it.cpplog"))
        except (RuntimeError, OSError):
            pytest.skip("native toolchain unavailable")
        pairs = _blobs(40)
        db.backend.store_batch([
            NodeObject(NodeObjectType.ACCOUNT_NODE, k, b) for k, b in pairs
        ])
        got = sorted((o.hash, int(o.type), o.data)
                     for o in db.backend.iterate())
        want = sorted((k, int(NodeObjectType.ACCOUNT_NODE), b)
                      for k, b in pairs)
        assert got == want
        db.close()

    def test_iterate_python_fallback_scan(self, tmp_path):
        """The file-scan fallback (stale native library without the
        iterate symbol) must return the same records."""
        try:
            db = make_database(type="cpplog",
                               path=str(tmp_path / "it2.cpplog"))
        except (RuntimeError, OSError):
            pytest.skip("native toolchain unavailable")
        pairs = _blobs(25)
        db.backend.store_batch([
            NodeObject(NodeObjectType.ACCOUNT_NODE, k, b) for k, b in pairs
        ])
        got = sorted((k, t, b) for k, t, b in db.backend._scan_log())
        want = sorted((k, int(NodeObjectType.ACCOUNT_NODE), b)
                      for k, b in pairs)
        assert got == want
        db.close()

    def test_iterate_roundtrips_compressed_records(self, tmp_path):
        try:
            db = make_database(type="cpplog",
                               path=str(tmp_path / "itz.cpplog"),
                               compression="zlib")
        except (RuntimeError, OSError):
            pytest.skip("native toolchain unavailable")
        # highly compressible blobs so the zlib flag actually fires
        pairs = [(sha512_half(b"Z" * (100 + i)), b"Z" * (100 + i))
                 for i in range(10)]
        db.backend.store_batch([
            NodeObject(NodeObjectType.ACCOUNT_NODE, k, b) for k, b in pairs
        ])
        got = sorted((o.hash, o.data) for o in db.backend.iterate())
        assert got == sorted(pairs)
        db.close()


class TestSqliteWalHygiene:
    def test_wal_stays_bounded_under_flood(self, tmp_path):
        path = str(tmp_path / "nodes.sqlite")
        db = make_database(type="sqlite", path=path)
        db.backend.WAL_CHECKPOINT_BYTES = 1 << 16  # test-scale threshold
        for chunk in range(40):
            pairs = _blobs(50, tag=f"wal{chunk}", size=96)
            db.backend.store_batch([
                NodeObject(NodeObjectType.ACCOUNT_NODE, k, b)
                for k, b in pairs
            ])
        assert db.backend.wal_checkpoints >= 1
        wal = os.path.getsize(path + "-wal")
        # bounded: far below the ~400KB written; TRUNCATE resets to a
        # small tail (the post-checkpoint commits)
        assert wal < 2 * db.backend.WAL_CHECKPOINT_BYTES, wal
        db.close()

    def test_synchronous_passthrough_and_validation(self, tmp_path):
        db = make_database(type="sqlite",
                           path=str(tmp_path / "s.sqlite"),
                           synchronous="off")
        level = db.backend._conn.execute("PRAGMA synchronous").fetchone()[0]
        assert level == 0  # OFF
        db.close()
        with pytest.raises(ValueError):
            make_database(type="sqlite",
                          path=str(tmp_path / "s2.sqlite"),
                          synchronous="everything")


class TestDatabaseFacade:
    def test_store_packed_falls_back_for_plain_backends(self):
        db = make_database(type="memory")
        pairs = _blobs(20)
        assert _store_packed(db, pairs) == 20
        for k, b in pairs:
            assert db.fetch(k).data == b

    def test_get_json_shape(self, tmp_path, use_native):
        db = make_database(type="segstore", path=str(tmp_path / "ns"),
                           use_native=use_native)
        _store_packed(db, _blobs(10))
        db.fetch(_blobs(10)[0][0])
        db.fetch(b"\x01" * 32)
        j = db.get_json()
        assert j["backend"] == "segstore"
        assert j["backend_fetches"] >= 1
        assert j["backend_misses"] >= 1
        bs = j["backend_stats"]
        for field in ("appends", "records", "bytes_appended", "fsyncs",
                      "segments", "disk_bytes", "live_bytes",
                      "live_ratio", "checkpoints", "compactions",
                      "sweeps", "replayed_records", "durability"):
            assert field in bs, field
        db.close()

    def test_sweep_unsupported_backend_raises(self):
        db = make_database(type="memory")
        with pytest.raises(NotImplementedError):
            db.begin_sweep()
        with pytest.raises(NotImplementedError):
            db.apply_sweep(set())


class TestLedgerThroughSegstore:
    def test_ledger_save_load_roundtrip(self, tmp_path, use_native):
        from stellard_tpu.protocol.keys import KeyPair
        from stellard_tpu.state.ledger import Ledger

        db = make_database(type="segstore", path=str(tmp_path / "ns"),
                           use_native=use_native)
        genesis = Ledger.genesis(
            KeyPair.from_passphrase("masterpassphrase").account_id
        )
        h = genesis.save(db)
        db.sync()
        loaded = Ledger.load(db, h)
        assert loaded.hash() == h
        assert loaded.state_map.get_hash() == genesis.state_map.get_hash()
        # delta-only on re-save: the known-set short-circuits everything
        before = db.backend.records
        genesis.save(db)
        assert db.backend.records == before
        db.close()

    def test_flush_packed_matches_store_many(self, tmp_path, use_native):
        """SHAMap.flush through the packed door lands byte-identical
        nodes to the store_many door (the pre-PR path)."""
        import hashlib as _h

        from stellard_tpu.state.shamap import SHAMap, SHAMapItem, TNType

        m = SHAMap(TNType.ACCOUNT_STATE)
        for i in range(200):
            tag = _h.sha256(f"flush:{i}".encode()).digest()
            m.set_item(SHAMapItem(tag, _h.sha512(tag).digest()))
        db_p = make_database(type="segstore", path=str(tmp_path / "p"),
                             use_native=use_native)
        n_p = m.flush(
            db_p.store_fn(NodeObjectType.ACCOUNT_NODE), set(),
            store_packed=db_p.store_packed_fn(NodeObjectType.ACCOUNT_NODE),
        )
        db_m = make_database(type="memory")
        n_m = m.flush(
            db_m.store_fn(NodeObjectType.ACCOUNT_NODE), set(),
            store_many=db_m.store_many_fn(NodeObjectType.ACCOUNT_NODE),
        )
        assert n_p == n_m
        db_m.sync()
        for obj in db_m.backend.iterate():
            got = db_p.fetch(obj.hash)
            assert got is not None and got.data == obj.data
        db_p.close()


class TestNodeDbConfig:
    def test_node_db_stanza_parses(self):
        from stellard_tpu.node.config import Config

        cfg = Config.from_ini(
            "[node_db]\n"
            "type=segstore\n"
            "path=/tmp/x\n"
            "durability=batch\n"
            "group_commit_ms=12.5\n"
            "segment_mb=8\n"
            "checkpoint_mb=4\n"
            "compact_ratio=0.25\n"
            "online_delete=256\n"
            "online_delete_interval=64\n"
        )
        assert cfg.node_db_type == "segstore"
        assert cfg.node_db_durability == "batch"
        assert cfg.node_db_group_commit_ms == 12.5
        assert cfg.node_db_segment_mb == 8
        assert cfg.node_db_checkpoint_mb == 4
        assert cfg.node_db_compact_ratio == 0.25
        assert cfg.node_db_online_delete == 256
        assert cfg.node_db_online_delete_interval == 64

    def test_bad_durability_rejected(self):
        from stellard_tpu.node.config import Config

        with pytest.raises(ValueError):
            Config.from_ini("[node_db]\ntype=segstore\ndurability=fast\n")

    def test_online_delete_requires_liveness_backend(self, tmp_path):
        from stellard_tpu.node.config import Config
        from stellard_tpu.node.node import Node

        with pytest.raises(ValueError):
            Node(Config(node_db_type="memory", node_db_online_delete=8))


class TestNodeOnSegstore:
    def test_flood_with_online_deletion_bounded_and_resolvable(
            self, tmp_path):
        """End-to-end: a standalone node on segstore floods payments
        with online deletion on; retained ledgers stay fully
        resolvable, early history is swept, disk stays within 2x the
        live set."""
        import threading

        from stellard_tpu.node.config import Config
        from stellard_tpu.node.node import Node
        from stellard_tpu.protocol.formats import TxType
        from stellard_tpu.protocol.keys import KeyPair
        from stellard_tpu.protocol.sfields import sfAmount, sfDestination
        from stellard_tpu.protocol.stamount import STAmount
        from stellard_tpu.protocol.sttx import SerializedTransaction
        from stellard_tpu.state.ledger import Ledger

        node = Node(Config(
            node_db_type="segstore",
            node_db_path=str(tmp_path / "nodestore"),
            node_db_online_delete=3,
            node_db_online_delete_interval=2,
            node_db_segment_mb=1,
            database_path=str(tmp_path / "stellard.db"),
        )).setup()
        try:
            master = KeyPair.from_passphrase("masterpassphrase")
            dests = [KeyPair.from_passphrase(f"od-{i}").account_id
                     for i in range(4)]
            done = threading.Semaphore(0)

            def cb(tx, ter, applied):
                done.release()

            seq = 1
            for _close in range(8):
                txs = []
                for i in range(20):
                    tx = SerializedTransaction.build(
                        TxType.ttPAYMENT, master.account_id, seq, 10,
                        {sfAmount: STAmount.from_drops(250_000_000),
                         sfDestination: dests[i % len(dests)]},
                    )
                    tx.sign(master)
                    txs.append(tx)
                    seq += 1
                for tx in txs:
                    node.ops.submit_transaction(tx, cb)
                for _ in txs:
                    done.acquire()
                node.close_ledger()
            deadline = 30.0
            import time as _t

            while node.online_deleter.get_json()["sweeps_completed"] < 1 \
                    and deadline > 0:
                _t.sleep(0.1)
                deadline -= 0.1
            node.close_pipeline.flush(timeout=30)
            od = node.online_deleter.get_json()
            assert od["sweeps_completed"] >= 1, od
            lcl = node.ledger_master.closed_ledger()
            lo = od["last_retain_floor"]
            resolved = 0
            for s in range(lo, lcl.seq + 1):
                hdr = node.txdb.get_ledger_header(seq=s)
                if hdr is None:
                    continue
                led = Ledger.load(node.nodestore, hdr["hash"])
                assert led.hash() == hdr["hash"]
                resolved += 1
            assert resolved >= 2
            # early history swept: the first post-genesis close's full
            # tree is gone from the store. Its txdb header may ALSO be
            # gone now — SQL rows rotate with the same horizon
            # ([node_db] sql_trim, default on)
            hdr1 = node.txdb.get_ledger_header(seq=2)
            if hdr1 is not None:
                with pytest.raises(KeyError):
                    Ledger.load(node.nodestore, hdr1["hash"])
            # the SQL mirror is bounded by the retention window, not the
            # whole run: rows below the retain floor were deleted on the
            # drain worker (ISSUE 9 satellite — disk-bound pin)
            assert od["sql_trim"] and od["sql_rows_trimmed"] > 0, od
            rows = node.txdb.counts()
            window = lcl.seq - lo + 1
            assert rows["ledgers"] <= window + 1, (rows, lo, lcl.seq)
            assert rows["transactions"] <= 20 * (window + 1), rows
            assert rows["account_transactions"] <= 2 * 20 * (window + 1)
            bs = node.nodestore.get_json()["backend_stats"]
            assert bs["disk_bytes"] <= 2 * max(bs["live_bytes"], 1) \
                + (1 << 16), bs
            # observability: the node_store block rides get_counts
            from stellard_tpu.rpc.handlers import Context, Role, dispatch

            counts = dispatch(
                Context(node, {}, Role.ADMIN), "get_counts"
            )
            assert counts["node_store"]["backend"] == "segstore"
            assert counts["node_store"]["online_delete"][
                "sweeps_completed"] >= 1
        finally:
            node.stop()

class TestSqlTrim:
    """TxDatabase.trim_below: the SQL half of online deletion."""

    def _db_with_history(self, n_ledgers=6, txs_per=3):
        from stellard_tpu.node.txdb import TxDatabase

        db = TxDatabase()

        class _L:
            def __init__(self, seq):
                self.seq = seq
                self.parent_hash = bytes([seq - 1]) * 32
                self.tot_coins = 0
                self.close_time = seq * 10
                self.parent_close_time = (seq - 1) * 10
                self.close_resolution = 10
                self.close_flags = 0
                self.account_hash = bytes([seq]) * 32
                self.tx_hash = bytes([seq]) * 32

            def hash(self):
                return bytes([self.seq]) * 32

        for seq in range(1, n_ledgers + 1):
            led = _L(seq)
            rows = []
            for i in range(txs_per):
                txid = bytes([seq, i]) + bytes(30)
                rows.append((
                    txid, "Payment", bytes([i]) * 20, i + 1, seq,
                    "tesSUCCESS", b"raw", b"meta",
                    [bytes([i]) * 20, bytes([i + 1]) * 20], i,
                ))
            db.save_ledger(led, rows)
            db.save_validation(led.hash(), b"\x07" * 32, seq * 10, b"v")
        return db

    def test_trim_below_deletes_history_keeps_window(self):
        db = self._db_with_history(n_ledgers=6, txs_per=3)
        before = db.counts()
        assert before == {
            "transactions": 18, "account_transactions": 36, "ledgers": 6,
        }
        deleted = db.trim_below(4)
        assert deleted["ledgers"] == 3
        assert deleted["transactions"] == 9
        assert deleted["account_transactions"] == 18
        assert deleted["validations"] == 3
        after = db.counts()
        assert after == {
            "transactions": 9, "account_transactions": 18, "ledgers": 3,
        }
        # the retained window is untouched and fully queryable
        assert db.get_ledger_header(seq=3) is None
        assert db.get_ledger_header(seq=4) is not None
        assert db.get_transaction(bytes([4, 0]) + bytes(30)) is not None
        assert db.get_transaction(bytes([3, 0]) + bytes(30)) is None
        # idempotent: a second trim at the same horizon is a no-op
        assert sum(db.trim_below(4).values()) == 0
        db.close()

    def test_account_tx_walk_survives_trim(self):
        db = self._db_with_history(n_ledgers=6, txs_per=3)
        db.trim_below(4)
        rows = db.account_transactions(bytes([0]) * 20)
        assert rows and all(r["ledger_seq"] >= 4 for r in rows)
        db.close()
