"""Multi-validator consensus over the deterministic simnet — the
reference's testoverlay-style coverage (SURVEY §4.2): a private net of
real ValidatorNodes exchanging wire frames, closing ledgers in
agreement, resolving disputes, and surviving partitions.
"""

from __future__ import annotations

import pytest

from stellard_tpu.overlay.simnet import SimNet
from stellard_tpu.overlay.wire import (
    FrameReader,
    GetLedger,
    Hello,
    LedgerData,
    Ping,
    ProposeSet,
    StatusChange,
    TxSetData,
    frame,
)
from stellard_tpu.protocol.formats import TxType
from stellard_tpu.protocol.keys import KeyPair
from stellard_tpu.protocol.sfields import sfAmount, sfBalance, sfDestination
from stellard_tpu.protocol.stamount import STAmount
from stellard_tpu.protocol.sttx import SerializedTransaction

XRP = 1_000_000
MASTER = KeyPair.from_passphrase("masterpassphrase")


def payment(key: KeyPair, seq: int, dest: bytes, drops: int) -> SerializedTransaction:
    tx = SerializedTransaction.build(
        TxType.ttPAYMENT, key.account_id, seq, 10,
        {sfAmount: STAmount.from_drops(drops), sfDestination: dest},
    )
    tx.sign(key)
    return tx


# -- wire codec -----------------------------------------------------------


class TestWire:
    def test_roundtrip_all_messages(self):
        h32 = bytes(range(32))
        msgs = [
            Hello(1, 99, b"\x02" * 32, b"\x03" * 64, 7, h32),
            Ping(False, 3),
            ProposeSet(2, 30, h32, h32, b"\x04" * 32, b"\x05" * 64),
            TxSetData(h32, [b"tx1", b"tx2"]),
            GetLedger(h32, 0, 2, [b"\x00", b"\x01\x23"]),
            LedgerData(h32, 9, 1, [(b"\x00", b"blob")]),
            StatusChange(4, 12, h32, 555),
        ]
        reader = FrameReader()
        stream = b"".join(frame(m) for m in msgs)
        # feed in awkward chunks to exercise reassembly
        out = []
        for i in range(0, len(stream), 7):
            out.extend(reader.feed(stream[i : i + 7]))
        assert len(out) == len(msgs)
        assert out[0].node_public == b"\x02" * 32
        assert out[2].propose_seq == 2
        assert out[3].tx_blobs == [b"tx1", b"tx2"]
        assert out[4].node_ids == [b"\x00", b"\x01\x23"]
        assert out[5].nodes == [(b"\x00", b"blob")]
        assert out[6].network_time == 555


# -- consensus over the simnet -------------------------------------------


class TestSimNetConsensus:
    def test_four_validators_agree_on_empty_ledgers(self):
        net = SimNet(4)
        net.start()
        assert net.run_until(lambda: net.all_validated_at_least(3), 60)
        for seq in (2, 3):
            assert len(net.validated_hashes_at(seq)) == 1  # no forks

    def test_payment_reaches_every_validator(self):
        net = SimNet(4)
        net.start()
        alice = KeyPair.from_passphrase("alice")
        tx = payment(MASTER, 1, alice.account_id, 1000 * XRP)
        net.validators[0].submit_client_tx(tx)
        base = net.validators[0].node.lm.validated.seq
        assert net.run_until(
            lambda: net.all_validated_at_least(base + 2), 60
        )
        for v in net.validators:
            led = v.node.lm.validated
            root = led.account_root(alice.account_id)
            assert root is not None
            assert root[sfBalance].drops() == 1000 * XRP

    def test_chain_of_payments_stays_in_agreement(self):
        net = SimNet(4)
        net.start()
        alice = KeyPair.from_passphrase("alice")
        bob = KeyPair.from_passphrase("bob")
        net.validators[0].submit_client_tx(
            payment(MASTER, 1, alice.account_id, 1000 * XRP)
        )
        net.run_until(lambda: net.all_validated_at_least(3), 60)
        net.validators[1].submit_client_tx(
            payment(MASTER, 2, bob.account_id, 500 * XRP)
        )
        net.validators[2].submit_client_tx(
            payment(alice, 1, bob.account_id, 100 * XRP)
        )
        seq0 = max(net.validated_seqs())
        assert net.run_until(
            lambda: net.all_validated_at_least(seq0 + 2), 80
        )
        hashes = {v.node.lm.validated.hash() for v in net.validators
                  if v.node.lm.validated.seq == max(net.validated_seqs())}
        balances = set()
        for v in net.validators:
            led = v.node.lm.validated
            balances.add(led.account_root(bob.account_id)[sfBalance].drops())
        assert balances == {600 * XRP}

    def test_three_node_quorum_survives_one_silent_node(self):
        # validator 3 is cut off entirely; 3-of-4 quorum still advances
        net = SimNet(4, quorum=3)
        net.start()
        for other in range(3):
            net.cut_link(3, other)
        assert net.run_until(
            lambda: all(
                s >= 3 for s in net.validated_seqs()[:3]
            ),
            80,
        )
        # the isolated node cannot advance
        assert net.validated_seqs()[3] <= 1

    def test_even_split_halts_then_heals(self):
        net = SimNet(4, quorum=3)
        net.start()
        net.run_until(lambda: net.all_validated_at_least(2), 40)
        net.partition({0, 1}, {2, 3})
        stalled_at = max(net.validated_seqs())
        net.step(30)
        # 2-2 split: neither side reaches 3-validator quorum → no
        # validated progress (safety over liveness)
        assert max(net.validated_seqs()) <= stalled_at + 1
        for a in (0, 1):
            for b in (2, 3):
                net.heal_link(a, b)
        healed_target = max(net.validated_seqs()) + 2
        assert net.run_until(
            lambda: net.all_validated_at_least(healed_target), 120
        )
        top = max(net.validated_seqs())
        assert len(net.validated_hashes_at(top)) == 1

    def test_disputed_tx_converges(self):
        # a tx submitted to only one validator right before close becomes
        # a dispute; avalanche voting must converge all nodes to ONE set
        net = SimNet(4, latency_steps=2)
        net.start()
        alice = KeyPair.from_passphrase("alice")
        tx = payment(MASTER, 1, alice.account_id, 1000 * XRP)
        # deliver to node 0 only; with 2-step latency peers may close
        # before seeing it
        net.validators[0].node.submit(tx)
        base = max(net.validated_seqs())
        assert net.run_until(lambda: net.all_validated_at_least(base + 3), 100)
        top = min(net.validated_seqs())
        assert len(net.validated_hashes_at(top)) == 1
        # the tx must eventually land everywhere (this round or a later one)
        for v in net.validators:
            led = v.node.lm.validated
            assert led.account_root(alice.account_id) is not None


class TestSimNetDeterminism:
    def test_two_runs_identical(self):
        def run():
            net = SimNet(4)
            net.start()
            alice = KeyPair.from_passphrase("alice")
            net.validators[1].submit_client_tx(
                payment(MASTER, 1, alice.account_id, 42 * XRP)
            )
            net.run_until(lambda: net.all_validated_at_least(4), 80)
            return [
                (nid, seq, h.hex()) for nid, seq, h in net.accept_log
            ]

        assert run() == run()


class TestByzantine:
    def test_equivocating_proposer_cannot_fork_honest_nodes(self):
        """One validator SIGNS two conflicting proposals per round and
        sends a different one to each half of the net (classic
        equivocation). Honest quorum (3 incl. own validation) must keep
        converging on ONE chain — validations, not proposals, decide
        (reference: LedgerConsensus disputes + Validations quorum)."""
        from stellard_tpu.consensus.proposal import LedgerProposal
        from stellard_tpu.overlay.simnet import ProposeSet, frame

        net = SimNet(4, quorum=3)
        byz = net.validators[3]
        real_propose = byz.propose

        calls = {"n": 0}

        def equivocate(proposal):
            calls["n"] += 1
            # half the peers get the real position...
            net.send(3, 0, frame(ProposeSet.from_proposal(proposal)))
            net.send(3, 1, frame(ProposeSet.from_proposal(proposal)))
            # ...the other peer gets a SIGNED conflicting position
            fake = LedgerProposal(
                prev_ledger=proposal.prev_ledger,
                propose_seq=proposal.propose_seq,
                tx_set_hash=b"\xEE" * 32,  # set nobody can acquire
                close_time=proposal.close_time,
            )
            fake.sign(byz.node.key)
            net.send(3, 2, frame(ProposeSet.from_proposal(fake)))

        byz.propose = equivocate
        net.start()

        alice = KeyPair.from_passphrase("byz-alice")
        net.validators[0].submit_client_tx(
            payment(MASTER, 1, alice.account_id, 1000 * XRP)
        )
        assert net.run_until(lambda: net.all_validated_at_least(4), 120), (
            "net stalled under an equivocating proposer"
        )
        # one chain: at every commonly-validated seq there is one hash
        top = min(net.validated_seqs())
        assert len(net.validated_hashes_at(top)) == 1, (
            f"fork under equivocation: {net.validated_hashes_at(top)}"
        )
        assert calls["n"] > 0, "equivocating proposer never proposed"
        # and the client tx still committed
        for v in net.validators:
            led = v.node.lm.validated
            assert led.account_root(alice.account_id) is not None


class TestRunawayRejoin:
    def test_solo_runaway_node_pulled_back_onto_net_chain(self):
        """An isolated validator keeps CLOSING rounds alone (closing
        needs no quorum) and runs ahead of the net on its own fork.
        After healing it must be pulled BACK onto the authoritative
        chain even though the net's validations carry lower seqs than
        its solo closes (the repair the closed-seq filter used to
        block)."""
        net = SimNet(4, quorum=3)
        net.start()
        net.run_until(lambda: net.all_validated_at_least(2), 40)
        for other in range(1, 4):
            net.cut_link(0, other)
        # let the isolated node solo-close well ahead while the majority
        # keeps validating its own chain
        majority_target = max(net.validated_seqs()[1:]) + 3
        assert net.run_until(
            lambda: all(s >= majority_target for s in net.validated_seqs()[1:]),
            80,
        )
        solo_closed = net.validators[0].node.lm.closed_ledger().seq
        assert solo_closed > 2, "isolated node never solo-closed"
        for other in range(1, 4):
            net.heal_link(0, other)
        # the runaway must converge onto the majority chain
        target = max(net.validated_seqs()) + 2
        assert net.run_until(
            lambda: net.all_validated_at_least(target), 120
        ), f"runaway node never rejoined: {net.validated_seqs()}"
        top = min(net.validated_seqs())
        assert len(net.validated_hashes_at(top)) == 1, (
            f"fork after rejoin: {net.validated_hashes_at(top)}"
        )
