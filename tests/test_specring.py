"""Shared-memory ring transport for the Block-STM pool (ISSUE 16).

The rings replace pickled pipes on the spec-pool hot path, so the seams
they add — the tagged binary codec, torn-slot detection, wraparound,
doorbell EOF, worker death mid-ring-write — must all degrade exactly the
way the pipe transport did: a corrupt or dead peer looks like a worker
death to the committer, the window completes through survivors or the
forced-serial drain, and the close NEVER wedges. Byte identity between
the ring and pipe transports (and serial) is pinned on the same
workloads the pipe transport was pinned on.
"""

from __future__ import annotations

import os
import signal
import threading

import pytest

from stellard_tpu.engine.specring import (
    TornSlotError,
    decode_msg,
    encode_msg,
    ring_pipe,
)
from stellard_tpu.engine.specexec import SpecExecutor
from stellard_tpu.node.config import Config, resolve_spec_workers
from stellard_tpu.node.ledgermaster import LedgerMaster

from test_parallel_spec import (
    MASTER,
    OPEN,
    dependent_chain,
    fresh,
    hot_account_burst,
    run_workload,
)


class TestCodec:
    """The pickle-free wire codec: everything the spec protocol sends
    must roundtrip exactly; anything else must refuse loudly."""

    @pytest.mark.parametrize("obj", [
        None, True, False, 0, 1, -1, 2**31, -(2**63), 2**200,
        0.0, 1.5, -3.25,
        b"", b"x" * 1000, "", "text", "é中",
        (), (1, 2), [1, [2, [3]]], {1: 2}, {b"k": (b"v", None)},
        set(), {1, 2, 3}, frozenset({b"a"}),
    ])
    def test_roundtrip(self, obj):
        got = decode_msg(encode_msg(obj))
        assert got == obj
        assert type(got) is type(obj)

    def test_roundtrip_wire_shapes(self):
        """The actual spec-protocol message vocabulary."""
        msgs = [
            ("win", 3, 17),
            ("exec", [(0, b"\x01" * 32, b"blob"), (1, b"\x02" * 32, b"")]),
            ("end",),
            ("stop",),
            ("rr", 5, b"k" * 32),
            ("sr", {"a": 1, "b": 2}),
            ("r", 7, 2, True, 100,
             [(b"succ", b"\x03" * 32), (b"gone", None)],
             {b"rk": b"PARENT", b"rk2": (b"\x04" * 32, 9)}),
            ("s", 1, 2, 3),
            ("resb", 0, b"payload"),
        ]
        for m in msgs:
            assert decode_msg(encode_msg(m)) == m

    def test_memoryview_and_bytearray_coerce_to_bytes(self):
        assert decode_msg(encode_msg(memoryview(b"abc"))) == b"abc"
        assert decode_msg(encode_msg(bytearray(b"abc"))) == b"abc"

    def test_unknown_tag_is_torn(self):
        with pytest.raises(TornSlotError):
            decode_msg(b"Qjunk")

    def test_trailing_garbage_is_torn(self):
        with pytest.raises(TornSlotError):
            decode_msg(encode_msg(1) + b"\x00")

    def test_truncation_is_torn(self):
        buf = encode_msg((b"payload", 123456789, "text"))
        for cut in range(1, len(buf)):
            with pytest.raises(TornSlotError):
                decode_msg(buf[:cut])

    def test_unencodable_type_raises(self):
        with pytest.raises(TypeError):
            encode_msg(object())


class TestRing:
    def test_send_recv_order(self):
        r, w = ring_pipe(capacity=1 << 16)
        try:
            msgs = [("exec", [(i, b"\x05" * 32, b"x" * i)]) for i in range(64)]
            for m in msgs:
                w.send(m)
            assert [r.recv() for _ in msgs] == msgs
            assert r.counters["msgs"] == 64
            assert w.counters["msgs"] == 64
        finally:
            r.close()
            w.destroy()

    def test_poll(self):
        r, w = ring_pipe(capacity=1 << 16)
        try:
            assert not r.poll(0)
            w.send(("s", 1))
            assert r.poll(1.0)
            assert r.recv() == ("s", 1)
            assert not r.poll(0)
        finally:
            r.close()
            w.destroy()

    def test_wraparound_hammer(self):
        """A ring much smaller than the traffic forces wrap-split
        records and producer full-waits; every message still arrives
        intact and in order."""
        r, w = ring_pipe(capacity=1 << 12)  # 4 KiB
        got = []

        def consume():
            while True:
                m = r.recv()
                if m == ("stop",):
                    return
                got.append(m)

        t = threading.Thread(target=consume)
        t.start()
        try:
            sent = []
            for i in range(500):
                m = ("resb", i, bytes([i & 0xFF]) * (i % 700))
                w.send(m)
                sent.append(m)
            w.send(("stop",))
            t.join(timeout=30)
            assert not t.is_alive()
            assert got == sent
            assert w.counters["full_waits"] > 0  # wrap actually exercised
        finally:
            r.close()
            w.destroy()

    def test_seeded_thread_hammer(self):
        """Seeded two-thread soak over one ring: random payload sizes
        spanning empty to multi-slot, exact order + content."""
        import random

        rng = random.Random(1234)
        r, w = ring_pipe(capacity=1 << 13)
        sent = [
            ("r", i, rng.randrange(4), rng.random() < 0.5,
             rng.randrange(10**9),
             [(rng.randbytes(32), rng.randbytes(32) if rng.random() < 0.7
               else None)],
             {rng.randbytes(32): b"PARENT"})
            for i in range(300)
        ]
        got = []
        t = threading.Thread(
            target=lambda: [got.append(r.recv()) for _ in sent]
        )
        t.start()
        try:
            for m in sent:
                w.send(m)
            t.join(timeout=30)
            assert not t.is_alive()
            assert got == sent
            assert r.counters["torn_slots"] == 0
        finally:
            r.close()
            w.destroy()

    def test_torn_slot_detected(self):
        """Corrupting a published record's payload in shared memory must
        surface as TornSlotError (an OSError — the committer's existing
        worker-death path), never as a silently-decoded wrong message."""
        r, w = ring_pipe(capacity=1 << 16)
        try:
            w.send(("exec", [(1, b"\x07" * 32, b"payload")]))
            # flip payload bytes behind the crc's back
            from stellard_tpu.engine.specring import _DATA_OFF

            buf = w._ring.buf
            buf[_DATA_OFF + 20] ^= 0xFF
            with pytest.raises(TornSlotError):
                r.recv()
            assert r.counters["torn_slots"] == 1
            assert isinstance(TornSlotError("x"), OSError)
        finally:
            r.close()
            w.destroy()

    def test_peer_close_is_eof(self):
        """A dead producer must look exactly like a closed pipe:
        EOFError from recv (the committer's worker-death signal)."""
        r, w = ring_pipe(capacity=1 << 16)
        w.send(("s", 1))
        # both ends live in THIS process, so drop the cross-copies by
        # hand (in the executor, settle() does this after fork) — the
        # reader must not keep the write fd alive itself
        r._peer_fd = -1
        w._peer_fd = -1
        w.close()
        try:
            assert r.recv() == ("s", 1)  # drained before EOF
            with pytest.raises(EOFError):
                r.recv()
        finally:
            r.destroy()


class TestRingTransportEndToEnd:
    def test_ring_vs_pipe_vs_serial_byte_identity(self):
        """The three transports must agree byte-for-byte on the
        conflict-heavy workload: serial inline, pickled pipes, rings."""
        phases = hot_account_burst()
        h0, r0, _s, _ = run_workload(phases, workers=1)
        for transport in ("ring", "pipe"):
            lm = LedgerMaster()
            ex = lm.spec_executor = SpecExecutor(
                workers=2, mode="process", transport=transport
            )
            lm.start_new_ledger(MASTER.account_id, close_time=1000)
            try:
                hashes, results_log = [], []
                for i, phase in enumerate(phases):
                    for tx in phase:
                        lm.do_transaction(fresh(tx), OPEN)
                    closed, results = lm.close_and_advance(2000 + i * 30, 30)
                    hashes.append(closed.hash())
                    results_log.append(sorted(
                        (txid.hex(), int(t)) for txid, t in results.items()
                    ))
                assert hashes == h0 and results_log == r0, transport
                j = ex.get_json()
                assert j["transport"] == transport
                assert j["worker_deaths"] == 0
                if transport == "ring":
                    # anti-vacuity: the traffic actually rode the rings
                    assert j["ring"]["msgs_sent"] > 0
                    assert j["ring"]["msgs_recv"] > 0
                    assert j["ring"]["torn_slots"] == 0
            finally:
                ex.stop()

    def test_sigkill_mid_window_recovers(self):
        """SIGKILL one worker mid-window (it may die holding a half-
        written ring slot); the committer must finish the window through
        the survivor or the drain — close byte-identical, never wedged."""
        phases = dependent_chain()
        h0, r0, _s, _ = run_workload(phases, workers=1)
        lm = LedgerMaster()
        ex = lm.spec_executor = SpecExecutor(
            workers=2, mode="process", transport="ring",
            drain_timeout_s=2.0,
        )
        lm.start_new_ledger(MASTER.account_id, close_time=1000)
        try:
            hashes, results_log = [], []
            killed = False
            for i, phase in enumerate(phases):
                for n, tx in enumerate(phase):
                    lm.do_transaction(fresh(tx), OPEN)
                    if not killed and i == 1 and n == len(phase) // 2:
                        killed = True
                        os.kill(ex._procs[0].proc.pid, signal.SIGKILL)
                        ex._procs[0].proc.join(timeout=5)
                closed, results = lm.close_and_advance(2000 + i * 30, 30)
                hashes.append(closed.hash())
                results_log.append(sorted(
                    (txid.hex(), int(t)) for txid, t in results.items()
                ))
            assert hashes == h0 and results_log == r0
            assert ex.get_json()["worker_deaths"] >= 1
        finally:
            ex.stop()

    def test_all_workers_sigkilled_drains_serial(self):
        """A fully dead ring pool must not wedge a close: the drain
        completes the window serially, byte-identical."""
        phases = dependent_chain()
        h0, r0, _s, _ = run_workload(phases, workers=1)
        lm = LedgerMaster()
        ex = lm.spec_executor = SpecExecutor(
            workers=2, mode="process", transport="ring",
            drain_timeout_s=2.0,
        )
        lm.start_new_ledger(MASTER.account_id, close_time=1000)
        try:
            hashes, results_log = [], []
            killed = False
            for i, phase in enumerate(phases):
                for n, tx in enumerate(phase):
                    lm.do_transaction(fresh(tx), OPEN)
                    if not killed and n == len(phase) // 2:
                        killed = True
                        for w in ex._procs:
                            os.kill(w.proc.pid, signal.SIGKILL)
                            w.proc.join(timeout=5)
                closed, results = lm.close_and_advance(2000 + i * 30, 30)
                hashes.append(closed.hash())
                results_log.append(sorted(
                    (txid.hex(), int(t)) for txid, t in results.items()
                ))
            assert hashes == h0 and results_log == r0
        finally:
            ex.stop()


class TestWorkersAuto:
    """[spec] workers=auto (ISSUE 16): sized from the box, disabled
    loudly below 4 cores, typos rejected at build per the dead-config
    convention."""

    def test_auto_small_box_disables_pool(self, caplog):
        import logging

        with caplog.at_level(logging.WARNING, logger="stellard.spec"):
            got = resolve_spec_workers(
                "auto", cpu_count=2,
                log=logging.getLogger("stellard.spec"),
            )
        assert got == 1
        assert any("DISABLED" in r.message for r in caplog.records)

    @pytest.mark.parametrize("cores,want", [
        (4, 4), (6, 6), (8, 8), (16, 8), (64, 8),
    ])
    def test_auto_sizes_from_cpu_count(self, cores, want):
        assert resolve_spec_workers("auto", cpu_count=cores) == want

    def test_explicit_int_passes_through(self):
        assert resolve_spec_workers(3, cpu_count=1) == 3
        assert resolve_spec_workers("2", cpu_count=1) == 2

    def test_ini_accepts_auto_and_int(self):
        assert Config.from_ini(
            "[spec]\nworkers=auto\n"
        ).spec_workers == "auto"
        assert Config.from_ini("[spec]\nworkers=6\n").spec_workers == 6

    def test_ini_accepts_transports(self):
        assert Config.from_ini(
            "[spec]\ntransport=pipe\n"
        ).spec_transport == "pipe"
        assert Config.from_ini("[spec]\n").spec_transport == "ring"

    def test_ini_rejects_typo(self):
        with pytest.raises(ValueError, match="workers"):
            Config.from_ini("[spec]\nworkers=lots\n")

    def test_ini_rejects_bad_transport(self):
        with pytest.raises(ValueError, match="transport"):
            Config.from_ini("[spec]\ntransport=tcp\n")

    def test_executor_rejects_bad_transport(self):
        with pytest.raises(ValueError, match="transport"):
            SpecExecutor(workers=2, transport="tcp")
