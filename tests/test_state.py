"""State-plane tests: SHAMap, NodeStore, Ledger, LedgerEntrySet.

Mirrors the reference's suites: RadixMapTest.cpp (randomized radix ops),
nodestore/tests/{BackendTests,BasicTests} (random batch round-trips),
ledger save/load, and directory/metadata behavior of LedgerEntrySet.
"""

import hashlib
import os

import pytest

from stellard_tpu.nodestore import NodeObjectType, make_database
from stellard_tpu.protocol.formats import LedgerEntryType
from stellard_tpu.protocol.sfields import (
    sfAffectedNodes,
    sfBalance,
    sfIndexes,
    sfLedgerEntryType,
    sfSequence,
)
from stellard_tpu.protocol.stobject import STObject
from stellard_tpu.protocol.ter import TER
from stellard_tpu.state import Ledger, LedgerEntrySet, SHAMap, SHAMapItem, TNType
from stellard_tpu.state import indexes
from stellard_tpu.state.shamap import (
    ZERO256,
    compute_hashes,
    deserialize_node_prefix,
    deserialize_node_wire,
    serialize_node_prefix,
    serialize_node_wire,
)
from stellard_tpu.utils.hashes import HP_INNER_NODE, HP_TXN_ID, prefix_hash


def h(i: int) -> bytes:
    return hashlib.sha256(i.to_bytes(8, "big")).digest()


# --------------------------------------------------------------------------
# SHAMap


class TestSHAMap:
    def test_empty_hash_is_zero(self):
        assert SHAMap().get_hash() == ZERO256

    def test_single_item_roundtrip(self):
        m = SHAMap()
        m.set_item(SHAMapItem(h(1), b"payload"))
        assert m.get(h(1)).data == b"payload"
        assert m.get(h(2)) is None
        assert len(m) == 1

    def test_insert_order_independence(self):
        """Same items in any order -> same root hash (Merkle determinism)."""
        items = [(h(i), bytes([i]) * 10) for i in range(50)]
        m1, m2 = SHAMap(), SHAMap()
        for tag, data in items:
            m1.set_item(SHAMapItem(tag, data))
        for tag, data in reversed(items):
            m2.set_item(SHAMapItem(tag, data))
        assert m1.get_hash() == m2.get_hash()

    def test_update_changes_hash(self):
        m = SHAMap()
        m.set_item(SHAMapItem(h(1), b"a"))
        h1 = m.get_hash()
        m.set_item(SHAMapItem(h(1), b"b"))
        assert m.get_hash() != h1
        m.set_item(SHAMapItem(h(1), b"a"))
        assert m.get_hash() == h1

    def test_delete_restores_hash(self):
        """Mirror of RadixMapTest: add/remove returns to prior state."""
        m = SHAMap()
        for i in range(40):
            m.set_item(SHAMapItem(h(i), h(i) + h(i)))
        before = m.get_hash()
        m.set_item(SHAMapItem(h(999), b"x"))
        assert m.get_hash() != before
        m.del_item(h(999))
        assert m.get_hash() == before

    def test_delete_missing_raises(self):
        m = SHAMap()
        m.set_item(SHAMapItem(h(1), b"a"))
        with pytest.raises(KeyError):
            m.del_item(h(2))

    def test_snapshot_isolation(self):
        m = SHAMap()
        for i in range(20):
            m.set_item(SHAMapItem(h(i), b"v%d" % i))
        snap = m.snapshot()
        snap_hash = snap.get_hash()
        m.set_item(SHAMapItem(h(100), b"new"))
        m.del_item(h(3))
        assert snap.get_hash() == snap_hash
        assert snap.get(h(3)) is not None
        assert m.get(h(3)) is None

    def test_iteration_sorted(self):
        m = SHAMap()
        tags = [h(i) for i in range(30)]
        for t in tags:
            m.set_item(SHAMapItem(t, b"d"))
        walked = [it.tag for it in m.items()]
        assert walked == sorted(tags)

    def test_succ(self):
        m = SHAMap()
        tags = sorted(h(i) for i in range(10))
        for t in tags:
            m.set_item(SHAMapItem(t, b"d"))
        assert m.succ(tags[0]).tag == tags[1]
        assert m.succ(b"\x00" * 32).tag == tags[0]
        assert m.succ(tags[-1]) is None

    def test_compare_delta(self):
        m1 = SHAMap()
        for i in range(100):
            m1.set_item(SHAMapItem(h(i), b"v"))
        m2 = m1.snapshot()
        m2.set_item(SHAMapItem(h(100), b"new"))  # added
        m2.set_item(SHAMapItem(h(5), b"changed"))  # modified
        m2.del_item(h(7))  # deleted
        delta = m1.compare(m2)
        assert set(delta) == {h(100), h(5), h(7)}
        assert delta[h(100)] == (None, m2.get(h(100)))
        assert delta[h(5)][0].data == b"v" and delta[h(5)][1].data == b"changed"
        assert delta[h(7)][1] is None

    def test_inner_node_hash_formula(self):
        """Inner hash = prefixed SHA-512-half over 16 child hashes
        (reference: SHAMapTreeNode.cpp:253-260)."""
        m = SHAMap()
        m.set_item(SHAMapItem(h(1), b"a"))
        m.set_item(SHAMapItem(h(2), b"b"))
        m.get_hash()
        root = m.root
        manual = prefix_hash(
            HP_INNER_NODE,
            b"".join((c._hash if c else ZERO256) for c in root.children),
        )
        assert manual == m.get_hash()

    def test_tx_leaf_hash_is_txid(self):
        """TX_NM leaf hash = SHA512half(TXN prefix || tx) == the tx ID."""
        m = SHAMap(TNType.TX_NM)
        blob = b"fake transaction bytes"
        txid = prefix_hash(HP_TXN_ID, blob)
        m.set_item(SHAMapItem(txid, blob))
        compute_hashes(m.root)
        leaf = m.root.children[txid[0] >> 4]
        assert leaf._hash == txid

    def test_node_serialization_roundtrip(self):
        m = SHAMap()
        for i in range(20):
            m.set_item(SHAMapItem(h(i), b"data%d" % i))
        m.get_hash()
        # leaf round-trip, both formats
        leaf = next(iter(_leaves(m.root)))
        for ser, deser in [
            (serialize_node_prefix, deserialize_node_prefix),
            (serialize_node_wire, deserialize_node_wire),
        ]:
            out = deser(ser(leaf))
            assert out.item.tag == leaf.item.tag
            assert out.item.data == leaf.item.data
            assert out.type == leaf.type
        # inner round-trip, both formats
        for ser, deser in [
            (serialize_node_prefix, deserialize_node_prefix),
            (serialize_node_wire, deserialize_node_wire),
        ]:
            stub = deser(ser(m.root))
            want = [(c._hash if c else ZERO256) for c in m.root.children]
            assert stub.child_hashes == want

    def test_wire_compressed_inner(self):
        """<12 branches uses the compressed wire encoding."""
        m = SHAMap()
        m.set_item(SHAMapItem(h(1), b"a"))
        m.set_item(SHAMapItem(h(2), b"b"))
        m.get_hash()
        blob = serialize_node_wire(m.root)
        assert blob[-1] == 3  # compressed trailer
        stub = deserialize_node_wire(blob)
        want = [(c._hash if c else ZERO256) for c in m.root.children]
        assert stub.child_hashes == want

    def test_flush_and_rebuild_from_store(self):
        db = make_database("memory", async_writes=False)
        m = SHAMap()
        for i in range(200):
            m.set_item(SHAMapItem(h(i), h(i) * 2))
        root_hash = m.get_hash()
        m.flush(db.store_fn(NodeObjectType.ACCOUNT_NODE))

        def fetch(hh):
            o = db.fetch(hh)
            return o.data if o else None

        m2 = SHAMap.from_store(root_hash, fetch)
        assert m2.get_hash() == root_hash
        assert len(m2) == 200
        for i in range(200):
            assert m2.get(h(i)).data == h(i) * 2

    def test_batched_hashing_matches_sequential(self):
        """Level-batched hashing == per-node hashing."""
        calls = []

        def spy_hasher(prefixes, payloads):
            calls.append(len(prefixes))
            return [prefix_hash(p, d) for p, d in zip(prefixes, payloads)]

        m = SHAMap(hash_batch=spy_hasher)
        ref = SHAMap()
        for i in range(300):
            m.set_item(SHAMapItem(h(i), b"x" * 40))
            ref.set_item(SHAMapItem(h(i), b"x" * 40))
        assert m.get_hash() == ref.get_hash()
        assert len(calls) > 1  # one call per level, not per node
        assert max(calls) > 50  # leaves batched together


def _leaves(node):
    from stellard_tpu.state.shamap import Inner, Leaf

    if isinstance(node, Leaf):
        yield node
    elif isinstance(node, Inner):
        for c in node.children:
            if c is not None:
                yield from _leaves(c)


# --------------------------------------------------------------------------
# NodeStore


class TestNodeStore:
    @pytest.mark.parametrize("backend", ["memory", "sqlite"])
    def test_roundtrip(self, backend, tmp_path):
        kwargs = {}
        if backend == "sqlite":
            kwargs["path"] = str(tmp_path / "nodes.db")
        db = make_database(backend, async_writes=False, **kwargs)
        blobs = {h(i): os.urandom(64) for i in range(100)}
        for k, v in blobs.items():
            db.store(NodeObjectType.ACCOUNT_NODE, k, v)
        for k, v in blobs.items():
            obj = db.fetch(k)
            assert obj is not None and obj.data == v
        assert db.fetch(h(10_000)) is None
        db.close()

    def test_async_writer_visibility(self):
        db = make_database("memory")
        for i in range(500):
            db.store(NodeObjectType.TRANSACTION_NODE, h(i), h(i))
        for i in range(500):  # reads see pending writes immediately
            assert db.fetch(h(i)).data == h(i)
        db.sync()
        assert db.backend.fetch(h(0)) is not None
        db.close()

    def test_sqlite_persistence(self, tmp_path):
        path = str(tmp_path / "n.db")
        db = make_database("sqlite", path=path)
        db.store(NodeObjectType.LEDGER, h(1), b"header")
        db.close()
        db2 = make_database("sqlite", path=path, async_writes=False)
        assert db2.fetch(h(1)).data == b"header"
        db2.close()

    def test_null_backend(self):
        db = make_database("null", async_writes=False)
        db.store(NodeObjectType.LEDGER, h(1), b"x")
        db.sync()
        assert db.backend.fetch(h(1)) is None

    def test_unknown_backend(self):
        with pytest.raises(KeyError):
            make_database("levelddb")


# --------------------------------------------------------------------------
# Ledger


ROOT = hashlib.sha256(b"root account").digest()[:20]


class TestLedger:
    def test_genesis(self):
        led = Ledger.genesis(ROOT)
        acct = led.account_root(ROOT)
        assert acct is not None
        assert acct[sfBalance].mantissa == led.tot_coins
        assert acct[sfSequence] == 1
        assert led.seq == 1

    def test_header_hash_changes_with_state(self):
        led = Ledger.genesis(ROOT)
        h1 = led.hash()
        led.write_entry(h(42), _mk_sle())
        assert led.hash() != h1

    def test_open_successor_chain(self):
        g = Ledger.genesis(ROOT)
        g.close(close_time=1000, close_resolution=30)
        child = g.open_successor()
        assert child.seq == 2
        assert child.parent_hash == g.hash()
        assert child.account_root(ROOT) is not None
        assert child.tx_map.get_hash() == ZERO256

    def test_tx_roundtrip(self):
        led = Ledger.genesis(ROOT)
        txid = led.add_transaction(b"txbytes", b"metabytes")
        assert txid == prefix_hash(HP_TXN_ID, b"txbytes")
        blob, meta = led.get_transaction(txid)
        assert (blob, meta) == (b"txbytes", b"metabytes")

    def test_save_load_roundtrip(self):
        db = make_database("memory", async_writes=False)
        led = Ledger.genesis(ROOT)
        for i in range(50):
            led.write_entry(h(i), _mk_sle(i))
        led.add_transaction(b"tx1", b"meta1")
        lh = led.save(db)
        led2 = Ledger.load(db, lh)
        assert led2.hash() == lh
        assert led2.seq == led.seq
        assert led2.tot_coins == led.tot_coins
        assert led2.read_entry(h(7)) == led.read_entry(h(7))
        assert led2.get_transaction(led.add_transaction(b"tx1", b"meta1"))


def _mk_sle(i: int = 0) -> STObject:
    sle = STObject()
    sle[sfLedgerEntryType] = int(LedgerEntryType.ltDIR_NODE)
    sle[sfSequence] = i
    return sle


# --------------------------------------------------------------------------
# LedgerEntrySet


class TestLedgerEntrySet:
    def test_peek_modify_apply(self):
        led = Ledger.genesis(ROOT)
        les = LedgerEntrySet(led)
        idx = indexes.account_root_index(ROOT)
        sle = les.peek(idx)
        sle[sfSequence] = 5
        les.modify(idx)
        les.apply()
        assert led.account_root(ROOT)[sfSequence] == 5

    def test_unapplied_changes_invisible(self):
        led = Ledger.genesis(ROOT)
        les = LedgerEntrySet(led)
        idx = indexes.account_root_index(ROOT)
        les.peek(idx)[sfSequence] = 99
        les.modify(idx)
        assert led.account_root(ROOT)[sfSequence] == 1  # not applied

    def test_create_then_erase_is_noop(self):
        led = Ledger.genesis(ROOT)
        les = LedgerEntrySet(led)
        les.create(LedgerEntryType.ltDIR_NODE, h(1))
        les.erase(h(1))
        les.apply()
        assert led.read_entry(h(1)) is None

    def test_dir_add_and_iterate(self):
        led = Ledger.genesis(ROOT)
        les = LedgerEntrySet(led)
        root_idx = indexes.owner_dir_index(ROOT)
        added = []
        for i in range(70):  # spans 3 pages (32 per page)
            ter, page = les.dir_add(root_idx, h(i))
            assert ter == TER.tesSUCCESS
            added.append((h(i), page))
        assert {p for _, p in added} == {0, 1, 2}
        assert set(les.dir_entries(root_idx)) == {h(i) for i in range(70)}

    def test_dir_delete(self):
        led = Ledger.genesis(ROOT)
        les = LedgerEntrySet(led)
        root_idx = indexes.owner_dir_index(ROOT)
        pages = {}
        for i in range(40):
            _, page = les.dir_add(root_idx, h(i))
            pages[h(i)] = page
        for i in range(40):
            assert les.dir_delete(root_idx, pages[h(i)], h(i)) == TER.tesSUCCESS
        les.apply()
        assert led.read_entry(root_idx) is None  # empty root deleted

    def test_metadata_created_modified_deleted(self):
        led = Ledger.genesis(ROOT)
        led.write_entry(h(2), _mk_sle(2))
        led.write_entry(h(3), _mk_sle(3))
        les = LedgerEntrySet(led)
        sle = les.create(LedgerEntryType.ltDIR_NODE, h(1))
        sle[sfSequence] = 1
        m = les.peek(h(2))
        m[sfSequence] = 22
        les.modify(h(2))
        les.erase(h(3))
        meta = les.calc_meta(TER.tesSUCCESS, 0, led.seq, h(99))
        nodes = {f.name: obj for f, obj in meta[sfAffectedNodes]}
        assert set(nodes) == {"CreatedNode", "ModifiedNode", "DeletedNode"}
        from stellard_tpu.protocol.sfields import (
            sfFinalFields,
            sfNewFields,
            sfPreviousFields,
        )

        assert nodes["CreatedNode"][sfNewFields][sfSequence] == 1
        assert nodes["ModifiedNode"][sfPreviousFields][sfSequence] == 2
        assert nodes["ModifiedNode"][sfFinalFields][sfSequence] == 22
        assert nodes["DeletedNode"][sfFinalFields][sfSequence] == 3
        # metadata serializes canonically
        blob = meta.serialize()
        assert STObject.from_bytes(blob) == meta

    def test_index_formulas_stable(self):
        """Golden stability of index namespaces (cross-checked against the
        reference construction: 2-byte space tag || fields, SHA-512-half)."""
        a = bytes(range(20))
        b = bytes(range(20, 40))
        cur = b"\x00" * 12 + b"USD\x00" + b"\x00" * 4  # 20-byte currency
        assert indexes.account_root_index(a) == prefix_hash_raw(b"\x00a" + a)
        assert indexes.ripple_state_index(a, b, cur) == indexes.ripple_state_index(
            b, a, cur
        )
        q = indexes.quality_index(h(5), 7)
        assert indexes.get_quality(q) == 7
        assert indexes.quality_next(q) > q


def prefix_hash_raw(data: bytes) -> bytes:
    return hashlib.sha512(data).digest()[:32]


# --------------------------------------------------------------------------
# regression tests for review findings


class TestReviewFindings:
    def test_delete_then_recreate_in_same_set(self):
        """Create-after-delete collapses to modify (LedgerEntrySet.cpp:176)."""
        led = Ledger.genesis(ROOT)
        les = LedgerEntrySet(led)
        root_idx = indexes.owner_dir_index(ROOT)
        ter, page = les.dir_add(root_idx, h(1))
        les.apply()
        les2 = LedgerEntrySet(led)
        assert les2.dir_delete(root_idx, 0, h(1)) == TER.tesSUCCESS
        assert les2.peek(root_idx) is None  # deleted reads as absent
        ter, page = les2.dir_add(root_idx, h(2))  # recreate in same set
        assert ter == TER.tesSUCCESS
        les2.apply()
        assert set(LedgerEntrySet(led).dir_entries(root_idx)) == {h(2)}

    def test_round_close_time_nearest(self):
        """reference Ledger::roundCloseTime rounds to NEAREST step."""
        assert Ledger.round_close_time(0, 30) == 0
        assert Ledger.round_close_time(29, 30) == 30
        assert Ledger.round_close_time(14, 30) == 0
        assert Ledger.round_close_time(15, 30) == 30
        assert Ledger.round_close_time(45, 30) == 60

    def test_succ_matches_walk(self):
        import random

        rng = random.Random(7)
        m = SHAMap()
        tags = sorted(h(rng.randrange(10**9)) for _ in range(200))
        for t in tags:
            m.set_item(SHAMapItem(t, b"d"))
        for probe in [b"\x00" * 32, tags[0], tags[57], tags[-1], b"\xff" * 32]:
            walk = next((t for t in tags if t > probe), None)
            got = m.succ(probe)
            assert (got.tag if got else None) == walk

    def test_flush_is_incremental(self):
        writes = []
        known: set = set()
        m = SHAMap()
        for i in range(100):
            m.set_item(SHAMapItem(h(i), b"v"))
        m.flush(lambda hh, d: writes.append(hh), known)
        first = len(writes)
        assert first > 100  # leaves + inners
        writes.clear()
        m.flush(lambda hh, d: writes.append(hh), known)
        assert writes == []  # nothing dirty
        m.set_item(SHAMapItem(h(0), b"changed"))
        m.flush(lambda hh, d: writes.append(hh), known)
        assert 0 < len(writes) <= 10  # just the changed path

    def test_writer_error_surfaces(self):
        from stellard_tpu.nodestore.core import Backend, Database

        class Boom(Backend):
            def store_batch(self, batch):
                raise OSError("disk full")

            def fetch(self, hash):
                return None

        db = Database(Boom())
        db.store(NodeObjectType.LEDGER, h(1), b"x")
        with pytest.raises(RuntimeError, match="writer failed"):
            db.sync()

    def test_wire_bad_branch_raises_valueerror(self):
        blob = b"\x00" * 32 + bytes([200]) + bytes([3])  # branch 200 invalid
        with pytest.raises(ValueError):
            deserialize_node_wire(blob)

    def test_load_corrupt_header_raises(self):
        db = make_database("memory", async_writes=False)
        led = Ledger.genesis(ROOT)
        lh = led.save(db)
        obj = db.fetch(lh)
        bad = bytearray(obj.data)
        bad[8] ^= 0xFF  # corrupt totCoins in stored header
        db.store(NodeObjectType.LEDGER, lh, bytes(bad))
        with pytest.raises(ValueError, match="hash mismatch"):
            Ledger.load(db, lh)

    def test_flush_to_second_store_writes_everything(self):
        """flush tracks stored-ness per store, not per node."""
        m = SHAMap()
        for i in range(50):
            m.set_item(SHAMapItem(h(i), b"v"))
        db_a = make_database("memory", async_writes=False)
        db_b = make_database("memory", async_writes=False)
        root = m.get_hash()
        m.flush(db_a.store_fn(NodeObjectType.ACCOUNT_NODE), db_a.flushed)
        n_b = m.flush(db_b.store_fn(NodeObjectType.ACCOUNT_NODE), db_b.flushed)
        assert n_b > 50  # everything written to the second store too

        def fetch_b(hh):
            o = db_b.fetch(hh)
            return o.data if o else None

        assert SHAMap.from_store(root, fetch_b).get_hash() == root

    def test_from_store_detects_corrupt_node(self):
        db = make_database("memory", async_writes=False)
        m = SHAMap()
        for i in range(20):
            m.set_item(SHAMapItem(h(i), b"v"))
        root = m.get_hash()
        m.flush(db.store_fn(NodeObjectType.ACCOUNT_NODE), db.flushed)
        # corrupt one stored leaf blob
        victim = next(o for o in db.backend.iterate()
                      if o.data[:4] == b"MLN\x00")
        bad = bytearray(victim.data)
        bad[-1] ^= 0xFF
        db.backend.store_batch([type(victim)(victim.type, victim.hash, bytes(bad))])
        db._cache.clear()

        def fetch(hh):
            o = db.fetch(hh)
            return o.data if o else None

        with pytest.raises(ValueError, match="content hash mismatch"):
            SHAMap.from_store(root, fetch)

    def test_stobject_copy_detaches_containers(self):
        sle = STObject()
        sle[sfLedgerEntryType] = 100
        sle[sfIndexes] = [h(1)]
        cp = sle.copy()
        cp[sfIndexes].append(h(2))
        assert sle[sfIndexes] == [h(1)]  # original untouched

    def test_open_tx_get_transaction(self):
        led = Ledger.genesis(ROOT)
        txid, added = led.add_open_transaction(b"\x12\x00\x34raw-tx")
        assert added
        blob, meta = led.get_transaction(txid)
        assert blob == b"\x12\x00\x34raw-tx" and meta == b""
