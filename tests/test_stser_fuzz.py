"""Byte-mutation fuzz over the STObject parser and the proto2 codec.

CI-sized pass of the corpus in tools/stser_fuzz.py (~10^5 deterministic
mutations of valid blobs: bit flips, truncations, length-field lies,
splices). The contract is crash-freedom — every case parses or raises;
a segfault/abort in the native extension kills the test process, which
IS the detection. `make -C native fuzz-asan` runs the same corpus under
-fsanitize=address,undefined for the overreads that don't crash a plain
build.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))

import stser_fuzz  # noqa: E402


class TestStserFuzz:
    def test_corpus_seeds_are_valid(self):
        """The corpus must start from blobs the parser accepts — fuzzing
        from garbage would only ever exercise the first reject branch."""
        from stellard_tpu.overlay import proto
        from stellard_tpu.protocol.stobject import STObject

        for blob in stser_fuzz.seed_blobs():
            obj = STObject.from_bytes(blob)
            assert obj.serialize() == blob
        for blob in stser_fuzz.proto_seed_blobs():
            assert proto.parse(blob)

    def test_mutation_corpus_never_crashes(self):
        cases = int(os.environ.get("STSER_FUZZ_CASES", "100000"))
        counts = stser_fuzz.run_corpus(cases=cases)
        assert counts["st_ok"] + counts["st_err"] == cases * 3 // 4
        assert counts["pb_ok"] + counts["pb_err"] == cases - cases * 3 // 4
        # both accept and reject branches must be exercised, or the
        # mutations aren't reaching past the envelope
        for k in counts:
            assert counts[k] > 0, counts

    def test_parse_is_deterministic_on_mutants(self):
        """Same mutant in, same outcome out (parse result bytes or the
        same exception type) — a parser with state bleed between calls
        would pass the crash check and still be broken."""
        import random

        from stellard_tpu.protocol.stobject import STObject

        rng = random.Random(7)
        seeds = stser_fuzz.seed_blobs()
        for _ in range(2000):
            blob = stser_fuzz.mutate(rng, rng.choice(seeds))

            def outcome():
                try:
                    return ("ok", STObject.from_bytes(blob).serialize())
                except Exception as e:  # noqa: BLE001 — compared by type
                    return ("err", type(e).__name__)

            assert outcome() == outcome()
