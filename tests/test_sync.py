"""SHAMap sync + InboundLedger + catch-up tests (reference coverage:
SHAMapSync.cpp suites, FetchPackTests.cpp, InboundLedger acquisition,
checkLastClosedLedger switch)."""

from __future__ import annotations

import hashlib

import pytest

from stellard_tpu.node.inbound import (
    InboundLedger,
    W_HEADER,
    W_STATE_TREE,
    W_TX_TREE,
    serve_get_ledger,
)
from stellard_tpu.overlay.simnet import SimNet
from stellard_tpu.overlay.wire import GetLedger
from stellard_tpu.protocol.formats import TxType
from stellard_tpu.protocol.keys import KeyPair
from stellard_tpu.protocol.sfields import sfAmount, sfBalance, sfDestination
from stellard_tpu.protocol.stamount import STAmount
from stellard_tpu.protocol.sttx import SerializedTransaction
from stellard_tpu.state.shamap import SHAMap, SHAMapItem, TNType
from stellard_tpu.state.shamapsync import (
    IncompleteMap,
    SHAMapNodeID,
    make_fetch_pack,
)

H = lambda n: hashlib.sha256(b"sync%d" % n).digest()
XRP = 1_000_000
MASTER = KeyPair.from_passphrase("masterpassphrase")


def build_map(n: int) -> SHAMap:
    m = SHAMap(TNType.ACCOUNT_STATE)
    for i in range(n):
        m.set_item(SHAMapItem(H(i), b"payload-%d" % i))
    m.get_hash()
    return m


class TestSHAMapNodeID:
    def test_child_paths_and_wire_roundtrip(self):
        nid = SHAMapNodeID.root()
        a = nid.child(0xA)
        b = a.child(0x3)
        assert b.nibbles() == [0xA, 0x3]
        assert SHAMapNodeID.decode(b.encode()) == b
        assert SHAMapNodeID.decode(a.encode()) != b

    def test_rejects_garbage(self):
        with pytest.raises(ValueError):
            SHAMapNodeID.decode(b"\x00" * 10)
        with pytest.raises(ValueError):
            SHAMapNodeID.decode(b"\x00" * 32 + b"\x7f")


class TestIncompleteMap:
    def test_full_acquisition_matches_source(self):
        src = build_map(50)
        pack = make_fetch_pack(src)
        imap = IncompleteMap(src.get_hash())
        assert not imap.is_complete()
        assert imap.add_nodes(list(pack)) == len(pack)
        assert imap.is_complete()
        rebuilt = imap.to_shamap()
        assert rebuilt.get_hash() == src.get_hash()
        assert rebuilt.get(H(17)).data == b"payload-17"

    def test_forged_node_rejected(self):
        src = build_map(10)
        pack = list(make_fetch_pack(src))
        h0, blob0 = pack[0]
        imap = IncompleteMap(src.get_hash())
        assert imap.add_nodes([(h0, blob0 + b"tamper")]) == 0
        assert not imap.have_node(h0)

    def test_incremental_bfs_requests(self):
        src = build_map(200)
        blob_by_hash = dict(make_fetch_pack(src))
        imap = IncompleteMap(src.get_hash())
        rounds = 0
        while not imap.is_complete():
            missing = imap.missing_nodes(limit=16)
            assert missing, "incomplete map must report missing nodes"
            imap.add_nodes([(h, blob_by_hash[h]) for _nid, h in missing])
            rounds += 1
            assert rounds < 1000
        assert imap.to_shamap().get_hash() == src.get_hash()

    def test_delta_fetch_pack_skips_shared(self):
        base = build_map(100)
        target = base.snapshot()
        target.set_item(SHAMapItem(H(999), b"new-item"))
        target.get_hash()
        delta = make_fetch_pack(target, base=base)
        full = make_fetch_pack(target)
        assert 0 < len(delta) < len(full)
        # delta + base nodes reconstruct the target
        store = dict(make_fetch_pack(base))
        store.update(dict(delta))
        imap = IncompleteMap(target.get_hash())
        imap.add_nodes(list(store.items()))
        assert imap.is_complete()


class TestInboundLedger:
    def _closed_ledger_pair(self):
        """A standalone node with one payment-bearing closed ledger."""
        from stellard_tpu.node.ledgermaster import LedgerMaster

        lm = LedgerMaster()
        lm.start_new_ledger(MASTER.account_id, close_time=1000)
        alice = KeyPair.from_passphrase("sync-alice")
        tx = SerializedTransaction.build(
            TxType.ttPAYMENT, MASTER.account_id, 1, 10,
            {
                sfAmount: STAmount.from_drops(500 * XRP),
                sfDestination: alice.account_id,
            },
        )
        tx.sign(MASTER)
        from stellard_tpu.engine.engine import TxParams

        ter, _ = lm.do_transaction(tx, TxParams.OPEN_LEDGER)
        assert int(ter) == 0
        closed, _ = lm.close_and_advance(2000, 30)
        return lm, closed

    def test_acquire_via_get_ledger_protocol(self):
        lm, closed = self._closed_ledger_pair()
        il = InboundLedger(closed.hash())
        rounds = 0
        while not il.is_complete():
            reqs = il.next_requests(per_tree=4)
            assert reqs
            for req in reqs:
                reply = serve_get_ledger(closed, req)
                if reply is None:
                    continue
                if reply.what == W_HEADER:
                    assert il.take_header(reply.nodes[0][1])
                else:
                    il.take_nodes(reply.what, reply.nodes)
            rounds += 1
            assert rounds < 500
        rebuilt = il.build_ledger()
        assert rebuilt.hash() == closed.hash()
        assert rebuilt.seq == closed.seq

    def test_header_forgery_rejected(self):
        _lm, closed = self._closed_ledger_pair()
        il = InboundLedger(closed.hash())
        header = closed.header_bytes()
        assert not il.take_header(header[:-1] + b"\xff")
        assert il.take_header(header)


class TestCatchUp:
    def test_isolated_validator_catches_up_after_heal(self):
        net = SimNet(4, quorum=3)
        net.start()
        for other in range(3):
            net.cut_link(3, other)
        # majority advances while 3 is dark
        assert net.run_until(
            lambda: all(s >= 4 for s in net.validated_seqs()[:3]), 120
        )
        assert net.validated_seqs()[3] <= 1
        for other in range(3):
            net.heal_link(3, other)
        # the straggler must acquire the network LCL and rejoin; then the
        # whole net keeps advancing together
        assert net.run_until(
            lambda: net.validated_seqs()[3] >= 4, 200
        ), net.validated_seqs()
        top = min(net.validated_seqs())
        assert len(net.validated_hashes_at(top)) == 1

    def test_catchup_carries_state_not_just_headers(self):
        net = SimNet(4, quorum=3)
        net.start()
        alice = KeyPair.from_passphrase("catchup-alice")
        for other in range(3):
            net.cut_link(3, other)
        tx = SerializedTransaction.build(
            TxType.ttPAYMENT, MASTER.account_id, 1, 10,
            {
                sfAmount: STAmount.from_drops(777 * XRP),
                sfDestination: alice.account_id,
            },
        )
        tx.sign(MASTER)
        net.validators[0].submit_client_tx(tx)
        assert net.run_until(
            lambda: all(s >= 4 for s in net.validated_seqs()[:3]), 120
        )
        for other in range(3):
            net.heal_link(3, other)
        assert net.run_until(lambda: net.validated_seqs()[3] >= 4, 200)
        led = net.validators[3].node.lm.validated
        root = led.account_root(alice.account_id)
        assert root is not None and root[sfBalance].drops() == 777 * XRP


class TestFatReplies:
    def test_serve_get_ledger_includes_children(self):
        """One reply carries the requested inner PLUS its children, so a
        sync descends two levels per round trip."""
        from stellard_tpu.node.inbound import (
            W_STATE_TREE,
            InboundLedger,
            serve_get_ledger,
        )
        from stellard_tpu.overlay.wire import GetLedger
        from stellard_tpu.state.ledger import Ledger
        from stellard_tpu.protocol.keys import KeyPair
        from stellard_tpu.protocol.formats import TxType
        from stellard_tpu.protocol.sfields import sfAmount, sfDestination
        from stellard_tpu.protocol.stamount import STAmount
        from stellard_tpu.protocol.sttx import SerializedTransaction
        from stellard_tpu.engine.engine import TransactionEngine, TxParams

        master = KeyPair.from_passphrase("masterpassphrase")
        led = Ledger.genesis(master.account_id)
        eng = TransactionEngine(led)
        for i in range(40):  # enough accounts to force inner depth
            dest = KeyPair.from_passphrase(f"fat-{i}")
            tx = SerializedTransaction.build(
                TxType.ttPAYMENT, master.account_id, i + 1, 10,
                {
                    sfAmount: STAmount.from_drops(200_000_000),
                    sfDestination: dest.account_id,
                },
            )
            tx.sign(master)
            ter, _ = eng.apply_transaction(tx, TxParams.NONE)
            assert int(ter) == 0
        led.close(close_time=1000, close_resolution=10)

        # ask for the state-tree root only
        reply = serve_get_ledger(
            led, GetLedger(led.hash(), 0, W_STATE_TREE, [])
        )
        assert reply is not None
        assert len(reply.nodes) > 1, "fat reply must include children"

        # a fresh acquirer consumes the whole multi-level reply
        il = InboundLedger(led.hash())
        assert il.take_header(led.header_bytes())
        got = il.take_nodes(W_STATE_TREE, reply.nodes)
        assert got == len(reply.nodes)


class TestRecentAcquisitions:
    """Late LedgerData for a just-finished acquisition must be treated as
    solicited (ADVICE r3: honest slower peers were charged
    FEE_UNWANTED_DATA and down-ranked after the fast peer completed the
    acquisition)."""

    def test_expired_acquisition_is_recently_done(self):
        from stellard_tpu.node.inbound import InboundLedgers

        sent = []
        inb = InboundLedgers(send=sent.append)
        h = b"\x07" * 32
        inb.acquire(h, for_lcl=True)
        assert h in inb.live and not inb.recently_done(h)
        assert inb.expire_stale(max_age_s=-1) == 1
        assert h not in inb.live
        assert inb.recently_done(h)
        # and it ages out
        inb._recent[h] -= inb.RECENT_TTL + 1
        assert not inb.recently_done(h)

    def test_completed_acquisition_is_recently_done(self):
        from stellard_tpu.node.inbound import InboundLedgers
        from stellard_tpu.node.inbound import serve_get_ledger, W_HEADER
        from stellard_tpu.overlay.wire import GetLedger
        from stellard_tpu.state.ledger import Ledger
        from stellard_tpu.protocol.keys import KeyPair

        master = KeyPair.from_passphrase("masterpassphrase")
        led = Ledger.genesis(master.account_id)
        led.close(close_time=1000, close_resolution=10)

        done = []
        inb = InboundLedgers(send=lambda req: None)
        inb.on_complete = done.append
        inb.acquire(led.hash(), for_lcl=True)
        reply = serve_get_ledger(led, GetLedger(led.hash(), 0, W_HEADER, []))
        assert inb.take_ledger_data(reply) >= 1
        # drive remaining requests until the acquisition completes
        for _ in range(16):
            if led.hash() not in inb.live:
                break
            reqs = list(inb.live[led.hash()].next_requests())
            assert reqs, "live acquisition must want something"
            for req in reqs:
                data = serve_get_ledger(led, req)
                assert data is not None
                inb.take_ledger_data(data)
        assert done, "acquisition must complete against its own source"
        assert inb.recently_done(led.hash())


class TestLclSwitchReindex:
    def test_orphaned_seqs_repointed_to_adopted_chain(self):
        """After an LCL switch, get_ledger_by_seq must serve the ADOPTED
        chain's ledgers at every index, not our pre-switch orphans —
        the mismatch the reference's LedgerHistory::handleMismatch
        repairs. Two masters fork from a common parent; ours closes two
        orphans, then adopts the network chain two ahead."""
        from stellard_tpu.node.ledgermaster import LedgerMaster

        ours = LedgerMaster()
        ours.start_new_ledger(MASTER.account_id, close_time=1000)
        ours.min_validations = 3  # networked: own closes are NOT validated
        net = LedgerMaster()
        net.start_new_ledger(MASTER.account_id, close_time=1000)
        assert ours.closed_ledger().hash() == net.closed_ledger().hash()

        # diverge: our chain closes seqs 2,3 with one tx; the network's
        # closes empty ledgers for 2,3 and advances to 4
        alice = KeyPair.from_passphrase("reindex-alice")
        tx = SerializedTransaction.build(
            TxType.ttPAYMENT, MASTER.account_id, 1, 10,
            {
                sfAmount: STAmount.from_drops(500 * XRP),
                sfDestination: alice.account_id,
            },
        )
        tx.sign(MASTER)
        from stellard_tpu.engine.engine import TxParams

        ours.do_transaction(tx, TxParams.OPEN_LEDGER)
        ours.close_and_advance(2000, 30)  # our seq 2 (with tx)
        ours.close_and_advance(2030, 30)  # our seq 3
        for t in (2000, 2030, 2060):
            net.close_and_advance(t, 30)  # network seqs 2,3,4 (empty)
        assert (
            ours.get_ledger_by_seq(2).hash() != net.get_ledger_by_seq(2).hash()
        )

        # adopt the network LCL; make its ancestry resolvable to us
        for seq in (2, 3, 4):
            led = net.get_ledger_by_seq(seq)
            ours.ledgers_by_hash.put(led.hash(), led)
        ours.switch_lcl(net.closed_ledger())

        for seq in (2, 3, 4):
            got = ours.get_ledger_by_seq(seq)
            assert got is not None
            assert got.hash() == net.get_ledger_by_seq(seq).hash(), seq

    def test_unresolvable_orphan_entries_dropped(self):
        """When the adopted chain's ancestry CANNOT be resolved (the
        real catch-up shape: only the tip was acquired), the orphan
        index entries above the validated floor are dropped — serving
        nothing beats serving a ledger the network never validated."""
        from stellard_tpu.node.ledgermaster import LedgerMaster

        ours = LedgerMaster()
        ours.start_new_ledger(MASTER.account_id, close_time=1000)
        ours.min_validations = 3  # networked: own closes are NOT validated
        net = LedgerMaster()
        net.start_new_ledger(MASTER.account_id, close_time=1000)

        alice = KeyPair.from_passphrase("reindex-bob")
        tx = SerializedTransaction.build(
            TxType.ttPAYMENT, MASTER.account_id, 1, 10,
            {
                sfAmount: STAmount.from_drops(500 * XRP),
                sfDestination: alice.account_id,
            },
        )
        tx.sign(MASTER)
        from stellard_tpu.engine.engine import TxParams

        ours.do_transaction(tx, TxParams.OPEN_LEDGER)
        ours.close_and_advance(2000, 30)  # orphan seq 2
        ours.close_and_advance(2030, 30)  # orphan seq 3
        for t in (2000, 2030, 2060):
            net.close_and_advance(t, 30)  # network 2,3,4

        # adopt ONLY the tip — ancestry unresolvable
        ours.switch_lcl(net.closed_ledger())
        assert ours.get_ledger_by_seq(4).hash() == net.closed_ledger().hash()
        for seq in (2, 3):
            got = ours.get_ledger_by_seq(seq)
            assert got is None or got.hash() == net.get_ledger_by_seq(seq).hash(), (
                f"seq {seq} still serves an orphan"
            )
        # the validated floor (genesis) survives
        assert ours.get_ledger_by_seq(1) is not None


class TestLocalDeltaResolution:
    def test_acquisition_completes_from_local_store_after_header(self):
        """With local_fetch wired to the NodeStore, an acquisition asks
        the wire for the HEADER only — every tree node resolves locally
        (the delta-sync shape of real catch-up: near-tip trees are
        shared)."""
        from stellard_tpu.node.inbound import InboundLedgers, serve_get_ledger
        from stellard_tpu.node.ledgermaster import LedgerMaster
        from stellard_tpu.nodestore.core import make_database
        from stellard_tpu.overlay.wire import GetLedger

        lm = LedgerMaster()
        lm.start_new_ledger(MASTER.account_id, close_time=1000)
        alice = KeyPair.from_passphrase("delta-alice")
        tx = SerializedTransaction.build(
            TxType.ttPAYMENT, MASTER.account_id, 1, 10,
            {
                sfAmount: STAmount.from_drops(700 * XRP),
                sfDestination: alice.account_id,
            },
        )
        tx.sign(MASTER)
        from stellard_tpu.engine.engine import TxParams

        lm.do_transaction(tx, TxParams.OPEN_LEDGER)
        closed, _ = lm.close_and_advance(2000, 30)
        db = make_database(type="memory")
        closed.save(db)

        sent: list[GetLedger] = []
        done: list = []

        def local_blob(h: bytes):
            obj = db.fetch(h)
            return obj.data if obj is not None else None

        ibs = InboundLedgers(send=sent.append, local_fetch=local_blob)
        ibs.on_complete = done.append
        ibs.acquire(closed.hash(), for_lcl=True)
        # the whole ledger (header + both trees) resolves locally:
        # NOTHING touches the wire
        assert sent == []
        assert done and done[0].hash() == closed.hash()
