"""4-validator private net over real TCP sockets (reference: the
Vagrant one-box testnet / 4-validator private net, SURVEY §4.4 and
BASELINE config #4). Clocks are accelerated 5× so consensus windows
(2s close, 3s establish) pass in ~1s real time each."""

from __future__ import annotations

import socket
import time

import pytest

from stellard_tpu.overlay.tcp import TcpOverlay
from stellard_tpu.protocol.formats import TxType
from stellard_tpu.protocol.keys import KeyPair
from stellard_tpu.protocol.sfields import sfAmount, sfBalance, sfDestination
from stellard_tpu.protocol.stamount import STAmount
from stellard_tpu.protocol.sttx import SerializedTransaction

XRP = 1_000_000
MASTER = KeyPair.from_passphrase("masterpassphrase")
SPEED = 5.0  # virtual seconds per real second


def free_ports(n: int) -> list[int]:
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


@pytest.fixture()
def net():
    n = 4
    ports = free_ports(n)
    keys = [KeyPair.from_passphrase(f"tcp-val-{i}") for i in range(n)]
    unl = {k.public for k in keys}
    t0 = time.monotonic()
    clock = lambda: (time.monotonic() - t0) * SPEED
    ntime = lambda: 20_000_000 + int(clock())
    overlays = []
    for i in range(n):
        peer_addrs = [("127.0.0.1", ports[j]) for j in range(n) if j != i]
        ov = TcpOverlay(
            key=keys[i],
            unl=unl,
            quorum=3,
            port=ports[i],
            peer_addrs=peer_addrs,
            network_time=ntime,
            clock=clock,
            timer_interval=0.15,
            idle_interval=4,
        )
        overlays.append(ov)
    for ov in overlays:
        ov.start(MASTER.account_id, close_time=ntime())
    yield overlays
    for ov in overlays:
        ov.stop()


def wait_until(pred, timeout=30.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.1)
    return pred()


class TestTcpPrivateNet:
    def test_connects_closes_and_agrees(self, net):
        assert wait_until(lambda: all(ov.peer_count() == 3 for ov in net), 15)
        assert wait_until(
            lambda: all(
                ov.node.lm.validated and ov.node.lm.validated.seq >= 3
                for ov in net
            ),
            30,
        ), [ov.node.lm.validated and ov.node.lm.validated.seq for ov in net]
        # same hash at a common validated seq on every node
        seq = min(ov.node.lm.validated.seq for ov in net)
        hashes = {ov.node.lm.ledger_history[seq] for ov in net}
        assert len(hashes) == 1

    def test_payment_commits_network_wide(self, net):
        assert wait_until(lambda: all(ov.peer_count() == 3 for ov in net), 15)
        alice = KeyPair.from_passphrase("alice")
        tx = SerializedTransaction.build(
            TxType.ttPAYMENT, MASTER.account_id, 1, 10,
            {
                sfAmount: STAmount.from_drops(1000 * XRP),
                sfDestination: alice.account_id,
            },
        )
        tx.sign(MASTER)
        net[2].submit_client_tx(tx)

        def landed():
            for ov in net:
                led = ov.node.lm.validated
                if led is None:
                    return False
                root = led.account_root(alice.account_id)
                if root is None or root[sfBalance].drops() != 1000 * XRP:
                    return False
            return True

        assert wait_until(landed, 30)
