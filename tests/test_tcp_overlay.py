"""4-validator private net over real TCP sockets (reference: the
Vagrant one-box testnet / 4-validator private net, SURVEY §4.4 and
BASELINE config #4). Clocks are accelerated 5× so consensus windows
(2s close, 3s establish) pass in ~1s real time each."""

from __future__ import annotations

import socket
import time

import pytest

from stellard_tpu.overlay.tcp import TcpOverlay
from stellard_tpu.protocol.formats import TxType
from stellard_tpu.protocol.keys import KeyPair
from stellard_tpu.protocol.sfields import sfAmount, sfBalance, sfDestination
from stellard_tpu.protocol.stamount import STAmount
from stellard_tpu.protocol.sttx import SerializedTransaction

XRP = 1_000_000
MASTER = KeyPair.from_passphrase("masterpassphrase")
SPEED = 5.0  # virtual seconds per real second


def free_ports(n: int) -> list[int]:
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


@pytest.fixture()
def net():
    n = 4
    ports = free_ports(n)
    keys = [KeyPair.from_passphrase(f"tcp-val-{i}") for i in range(n)]
    unl = {k.public for k in keys}
    t0 = time.monotonic()
    clock = lambda: (time.monotonic() - t0) * SPEED
    ntime = lambda: 20_000_000 + int(clock())
    overlays = []
    for i in range(n):
        peer_addrs = [("127.0.0.1", ports[j]) for j in range(n) if j != i]
        ov = TcpOverlay(
            key=keys[i],
            unl=unl,
            quorum=3,
            port=ports[i],
            peer_addrs=peer_addrs,
            network_time=ntime,
            clock=clock,
            timer_interval=0.15,
            idle_interval=4,
        )
        overlays.append(ov)
    for ov in overlays:
        ov.start(MASTER.account_id, close_time=ntime())
    yield overlays
    for ov in overlays:
        ov.stop()


def wait_until(pred, timeout=30.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.1)
    return pred()


class TestTcpPrivateNet:
    def test_connects_closes_and_agrees(self, net):
        assert wait_until(lambda: all(ov.peer_count() == 3 for ov in net), 15)
        assert wait_until(
            lambda: all(
                ov.node.lm.validated and ov.node.lm.validated.seq >= 3
                for ov in net
            ),
            30,
        ), [ov.node.lm.validated and ov.node.lm.validated.seq for ov in net]
        # same hash at a common validated seq on every node
        seq = min(ov.node.lm.validated.seq for ov in net)
        hashes = {ov.node.lm.ledger_history[seq] for ov in net}
        assert len(hashes) == 1

    def test_payment_commits_network_wide(self, net):
        assert wait_until(lambda: all(ov.peer_count() == 3 for ov in net), 15)
        alice = KeyPair.from_passphrase("alice")
        tx = SerializedTransaction.build(
            TxType.ttPAYMENT, MASTER.account_id, 1, 10,
            {
                sfAmount: STAmount.from_drops(1000 * XRP),
                sfDestination: alice.account_id,
            },
        )
        tx.sign(MASTER)
        net[2].submit_client_tx(tx)

        def landed():
            for ov in net:
                led = ov.node.lm.validated
                if led is None:
                    return False
                root = led.account_root(alice.account_id)
                if root is None or root[sfBalance].drops() != 1000 * XRP:
                    return False
            return True

        assert wait_until(landed, 30)


def _pair(tls_modes, quorum=2, unl_size=2):
    """Two-node net with per-node TLS config: tls_modes[i] is None
    (plaintext), 'allow', or 'require'."""
    import tempfile

    from stellard_tpu.overlay.peertls import PeerTLS

    ports = free_ports(2)
    keys = [KeyPair.from_passphrase(f"tls-pair-{i}") for i in range(2)]
    unl = {k.public for k in keys[:unl_size]}
    t0 = time.monotonic()
    clock = lambda: (time.monotonic() - t0) * SPEED
    ntime = lambda: 30_000_000 + int(clock())
    overlays = []
    for i in range(2):
        tls = None
        if tls_modes[i] is not None:
            tls = PeerTLS.from_state_dir(
                tempfile.mkdtemp(prefix="tls-test-"),
                required=(tls_modes[i] == "require"),
            )
        overlays.append(TcpOverlay(
            key=keys[i], unl=unl, quorum=quorum, port=ports[i],
            peer_addrs=[("127.0.0.1", ports[1 - i])],
            network_time=ntime, clock=clock,
            timer_interval=0.15, idle_interval=4, peer_tls=tls,
        ))
    for ov in overlays:
        ov.start(MASTER.account_id, close_time=ntime())
    return overlays


class TestPeerTLS:
    """Encrypted peer links (reference: every peer connection is
    anonymous SSL with the hello proving the node key against the
    session — PeerImp.h:88-90; VERDICT r3 missing #3)."""

    def test_tls_net_encrypts_and_closes(self):
        import ssl

        net = _pair(["require", "require"])
        try:
            assert wait_until(
                lambda: all(ov.peer_count() == 1 for ov in net), 15
            )
            for ov in net:
                for p in ov.peers.values():
                    assert isinstance(p.sock, ssl.SSLSocket)
                    assert p.sock.cipher()[1] == "TLSv1.2"
            seq0 = net[0].node.lm.closed_ledger().seq
            assert wait_until(
                lambda: all(
                    ov.node.lm.closed_ledger().seq > seq0 for ov in net
                ),
                30,
            ), "consensus must close ledgers over TLS"
        finally:
            for ov in net:
                ov.stop()

    def test_required_refuses_plaintext_peer(self):
        net = _pair(["require", None])
        try:
            time.sleep(3.0)  # several dial/accept cycles
            assert net[0].peer_count() == 0
            assert net[1].peer_count() == 0
        finally:
            for ov in net:
                ov.stop()

    def test_allow_mode_interops_with_plaintext(self):
        # mixed-net upgrade: the plaintext node's dial reaches the
        # TLS-allow node's autodetecting listener and peers in the clear
        net = _pair(["allow", None])
        try:
            assert wait_until(
                lambda: all(ov.peer_count() == 1 for ov in net), 15
            )
            seq0 = net[0].node.lm.closed_ledger().seq
            assert wait_until(
                lambda: all(
                    ov.node.lm.closed_ledger().seq > seq0 for ov in net
                ),
                30,
            )
        finally:
            for ov in net:
                ov.stop()

    def test_invalid_peer_ssl_value_rejected(self):
        from stellard_tpu.node.config import Config

        with pytest.raises(ValueError):
            Config.from_ini("[peer_ssl]\ntrue\n")
        assert Config.from_ini("[peer_ssl]\nrequire\n").peer_ssl == "require"
        assert Config.from_ini("[peer_ssl]\nallow\n").peer_ssl == "allow"
