"""4-validator private net over real TCP sockets (reference: the
Vagrant one-box testnet / 4-validator private net, SURVEY §4.4 and
BASELINE config #4). Clocks are accelerated 5× so consensus windows
(2s close, 3s establish) pass in ~1s real time each."""

from __future__ import annotations

import socket
import time

import pytest

from stellard_tpu.overlay.tcp import TcpOverlay
from stellard_tpu.protocol.formats import TxType
from stellard_tpu.protocol.keys import KeyPair
from stellard_tpu.protocol.sfields import sfAmount, sfBalance, sfDestination
from stellard_tpu.protocol.stamount import STAmount
from stellard_tpu.protocol.sttx import SerializedTransaction

XRP = 1_000_000
MASTER = KeyPair.from_passphrase("masterpassphrase")
SPEED = 5.0  # virtual seconds per real second


def free_ports(n: int) -> list[int]:
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


@pytest.fixture()
def net():
    n = 4
    ports = free_ports(n)
    keys = [KeyPair.from_passphrase(f"tcp-val-{i}") for i in range(n)]
    unl = {k.public for k in keys}
    t0 = time.monotonic()
    clock = lambda: (time.monotonic() - t0) * SPEED
    ntime = lambda: 20_000_000 + int(clock())
    overlays = []
    for i in range(n):
        peer_addrs = [("127.0.0.1", ports[j]) for j in range(n) if j != i]
        ov = TcpOverlay(
            key=keys[i],
            unl=unl,
            quorum=3,
            port=ports[i],
            peer_addrs=peer_addrs,
            network_time=ntime,
            clock=clock,
            timer_interval=0.15,
            idle_interval=4,
        )
        overlays.append(ov)
    for ov in overlays:
        ov.start(MASTER.account_id, close_time=ntime())
    yield overlays
    for ov in overlays:
        ov.stop()


def wait_until(pred, timeout=30.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.1)
    return pred()


class TestTcpPrivateNet:
    def test_connects_closes_and_agrees(self, net):
        assert wait_until(lambda: all(ov.peer_count() == 3 for ov in net), 15)
        assert wait_until(
            lambda: all(
                ov.node.lm.validated and ov.node.lm.validated.seq >= 3
                for ov in net
            ),
            30,
        ), [ov.node.lm.validated and ov.node.lm.validated.seq for ov in net]
        # same hash at a common validated seq on every node
        seq = min(ov.node.lm.validated.seq for ov in net)
        hashes = {ov.node.lm.ledger_history[seq] for ov in net}
        assert len(hashes) == 1

    def test_payment_commits_network_wide(self, net):
        assert wait_until(lambda: all(ov.peer_count() == 3 for ov in net), 15)
        alice = KeyPair.from_passphrase("alice")
        tx = SerializedTransaction.build(
            TxType.ttPAYMENT, MASTER.account_id, 1, 10,
            {
                sfAmount: STAmount.from_drops(1000 * XRP),
                sfDestination: alice.account_id,
            },
        )
        tx.sign(MASTER)
        net[2].submit_client_tx(tx)

        def landed():
            for ov in net:
                led = ov.node.lm.validated
                if led is None:
                    return False
                root = led.account_root(alice.account_id)
                if root is None or root[sfBalance].drops() != 1000 * XRP:
                    return False
            return True

        assert wait_until(landed, 30)


def _pair(tls_modes, quorum=2, unl_size=2):
    """Two-node net with per-node TLS config: tls_modes[i] is None
    (plaintext), 'allow', or 'require'."""
    import tempfile

    from stellard_tpu.overlay.peertls import PeerTLS

    ports = free_ports(2)
    keys = [KeyPair.from_passphrase(f"tls-pair-{i}") for i in range(2)]
    unl = {k.public for k in keys[:unl_size]}
    t0 = time.monotonic()
    clock = lambda: (time.monotonic() - t0) * SPEED
    ntime = lambda: 30_000_000 + int(clock())
    overlays = []
    for i in range(2):
        tls = None
        if tls_modes[i] is not None:
            tls = PeerTLS.from_state_dir(
                tempfile.mkdtemp(prefix="tls-test-"),
                required=(tls_modes[i] == "require"),
            )
        overlays.append(TcpOverlay(
            key=keys[i], unl=unl, quorum=quorum, port=ports[i],
            peer_addrs=[("127.0.0.1", ports[1 - i])],
            network_time=ntime, clock=clock,
            timer_interval=0.15, idle_interval=4, peer_tls=tls,
        ))
    for ov in overlays:
        ov.start(MASTER.account_id, close_time=ntime())
    return overlays


class TestPeerTLS:
    """Encrypted peer links (reference: every peer connection is
    anonymous SSL with the hello proving the node key against the
    session — PeerImp.h:88-90; VERDICT r3 missing #3)."""

    def test_tls_net_encrypts_and_closes(self):
        import ssl

        net = _pair(["require", "require"])
        try:
            assert wait_until(
                lambda: all(ov.peer_count() == 1 for ov in net), 15
            )
            for ov in net:
                for p in ov.peers.values():
                    assert isinstance(p.sock, ssl.SSLSocket)
                    assert p.sock.cipher()[1] == "TLSv1.2"
            seq0 = net[0].node.lm.closed_ledger().seq
            assert wait_until(
                lambda: all(
                    ov.node.lm.closed_ledger().seq > seq0 for ov in net
                ),
                30,
            ), "consensus must close ledgers over TLS"
        finally:
            for ov in net:
                ov.stop()

    def test_required_refuses_plaintext_peer(self):
        net = _pair(["require", None])
        try:
            time.sleep(3.0)  # several dial/accept cycles
            assert net[0].peer_count() == 0
            assert net[1].peer_count() == 0
        finally:
            for ov in net:
                ov.stop()

    def test_allow_mode_interops_with_plaintext(self):
        # mixed-net upgrade: the plaintext node's dial reaches the
        # TLS-allow node's autodetecting listener and peers in the clear
        net = _pair(["allow", None])
        try:
            assert wait_until(
                lambda: all(ov.peer_count() == 1 for ov in net), 15
            )
            seq0 = net[0].node.lm.closed_ledger().seq
            assert wait_until(
                lambda: all(
                    ov.node.lm.closed_ledger().seq > seq0 for ov in net
                ),
                30,
            )
        finally:
            for ov in net:
                ov.stop()

    def test_invalid_peer_ssl_value_rejected(self):
        from stellard_tpu.node.config import Config

        with pytest.raises(ValueError):
            Config.from_ini("[peer_ssl]\ntrue\n")
        assert Config.from_ini("[peer_ssl]\nrequire\n").peer_ssl == "require"
        assert Config.from_ini("[peer_ssl]\nallow\n").peer_ssl == "allow"


# ---------------------------------------------------------------------------
# peer-port abuse (reference: PeerImp dispatch + Resource charging,
# PeerImp.cpp:1459-1738; VERDICT r3 weak #5 — transport-layer adversarial
# depth)


import os as _os

from stellard_tpu.overlay.tcp import HP_SESSION, PROTO_VERSION
from stellard_tpu.overlay.wire import Hello, Ping, FrameReader, frame
from stellard_tpu.utils.hashes import prefix_hash


@pytest.fixture()
def victim():
    """One live validator whose peer port we attack with raw sockets.
    Function-scoped: abuse charges accumulate per-IP, so each test gets a
    clean resource table."""
    port = free_ports(1)[0]
    key = KeyPair.from_passphrase("fuzz-victim")
    t0 = time.monotonic()
    clock = lambda: (time.monotonic() - t0) * SPEED
    ntime = lambda: 35_000_000 + int(clock())
    ov = TcpOverlay(
        key=key, unl={key.public}, quorum=1, port=port,
        peer_addrs=[], network_time=ntime, clock=clock,
        timer_interval=0.2, idle_interval=4,
    )
    ov.start(MASTER.account_id, close_time=ntime())
    yield ov
    ov.stop()


def _plain_nonce() -> bytes:
    n = _os.urandom(32)
    while n[0] == 0x16:
        n = _os.urandom(32)
    return n


def _recv_exact(sock, n, timeout=10.0):
    sock.settimeout(timeout)
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise OSError("closed")
        buf += chunk
    return buf


def _connect(ov) -> socket.socket:
    return socket.create_connection(("127.0.0.1", ov.port), timeout=5.0)


def _handshake(ov, sock, key: KeyPair) -> Hello:
    """Complete a legitimate nonce+hello handshake from a raw socket;
    returns the victim's hello."""
    server_nonce = _recv_exact(sock, 32)
    nonce = _plain_nonce()
    sock.sendall(nonce)
    session_hash = prefix_hash(
        HP_SESSION, min(nonce, server_nonce) + max(nonce, server_nonce)
    )
    hello = Hello(
        PROTO_VERSION, 35_000_000, key.public, key.sign(session_hash),
        1, b"\x00" * 32, 0,
    )
    sock.sendall(frame(hello))
    reader = FrameReader()
    sock.settimeout(10.0)
    while True:
        data = sock.recv(65536)
        assert data, "victim closed during legit handshake"
        msgs = reader.feed(data)
        if msgs:
            assert isinstance(msgs[0], Hello)
            return msgs[0]


def _sock_closed(sock, timeout=10.0) -> bool:
    """True when the remote closes/resets within `timeout`."""
    sock.settimeout(timeout)
    try:
        while True:
            if sock.recv(65536) == b"":
                return True
    except (ConnectionResetError, BrokenPipeError):
        return True
    except OSError:
        return False


class TestPeerPortFuzz:
    def test_pre_handshake_garbage_dropped_node_survives(self, victim):
        s = _connect(victim)
        s.sendall(b"\x00" + _os.urandom(499))  # not a nonce+hello
        assert _sock_closed(s), "garbage session must be dropped"
        s.close()
        # the node is still healthy: a legitimate peer handshakes fine
        s2 = _connect(victim)
        _handshake(victim, s2, KeyPair.from_passphrase("fuzz-good"))
        s2.close()

    def test_oversized_length_header_charged_and_dropped(self, victim):
        before = victim.resources.balance(("127.0.0.1", 0))
        s = _connect(victim)
        _recv_exact(s, 32)
        s.sendall(_plain_nonce())
        # 4-byte length far beyond MAX_FRAME, then junk
        s.sendall((1 << 31).to_bytes(4, "big") + b"\x00\x01" + b"x" * 64)
        assert _sock_closed(s)
        s.close()
        assert victim.resources.balance(("127.0.0.1", 0)) > before, (
            "oversized frame must charge the endpoint"
        )

    def test_truncated_protobuf_after_valid_handshake(self, victim):
        before = victim.resources.balance(("127.0.0.1", 0))
        s = _connect(victim)
        _handshake(victim, s, KeyPair.from_passphrase("fuzz-trunc"))
        # valid frame header for a TxMessage, payload is cut-off garbage
        good = frame(Ping(False, 1))
        tx_type = (30).to_bytes(2, "big")  # mtTRANSACTION
        s.sendall((40).to_bytes(4, "big") + tx_type + b"\xde\xad" * 20)
        assert _sock_closed(s)
        s.close()
        assert victim.resources.balance(("127.0.0.1", 0)) > before

    def test_unimplemented_message_type_skipped_stream_survives(self, victim):
        s = _connect(victim)
        _handshake(victim, s, KeyPair.from_passphrase("fuzz-unknown"))
        # schema-known but unimplemented type (mtGET_CONTACTS=10): a full
        # ripple.proto peer routinely sends these — skipped, session lives
        s.sendall((4).to_bytes(4, "big") + (10).to_bytes(2, "big") + b"abcd")
        s.sendall(frame(Ping(False, 7)))  # then a valid ping
        reader = FrameReader()
        s.settimeout(10.0)
        got_pong = False
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and not got_pong:
            try:
                data = s.recv(65536)
            except socket.timeout:
                break
            if not data:
                break
            for m in reader.feed(data):
                if isinstance(m, Ping) and m.is_pong and m.seq == 7:
                    got_pong = True
        s.close()
        assert got_pong, "session must survive an unknown message type"

    def test_forged_hello_flood_escalates_to_admission_ban(self, victim):
        """Repeated invalid-signature hellos (cost 100 each, the
        reference's feeInvalidSignature) drive the per-IP balance past
        DROP: later connection attempts are refused at accept."""
        key = KeyPair.from_passphrase("fuzz-forger")
        for _ in range(20):
            s = _connect(victim)
            try:
                _recv_exact(s, 32)
                s.sendall(_plain_nonce())
                forged = Hello(
                    PROTO_VERSION, 35_000_000, key.public,
                    b"\x01" * 64,  # garbage session signature
                    1, b"\x00" * 32, 0,
                )
                s.sendall(frame(forged))
                _sock_closed(s, timeout=5.0)
            except OSError:
                pass  # already banned mid-loop: fine
            finally:
                s.close()
            if not victim.resources.should_admit(("127.0.0.1", 0)):
                break
        assert not victim.resources.should_admit(("127.0.0.1", 0)), (
            "sustained abuse must cross the drop threshold"
        )
        # a fresh connection is now closed without a nonce
        s = _connect(victim)
        assert _sock_closed(s, timeout=10.0), "banned IP must be refused"
        s.close()


class TestSlowReaderBackpressure:
    def test_send_queue_overflow_drops_peer_not_deadlock(self):
        """A peer that stops reading must be DROPPED when the bounded
        send queue fills; send() never blocks the caller (the relay /
        consensus threads)."""
        from stellard_tpu.overlay.tcp import _Peer

        a, b = socket.socketpair()
        # tiny kernel buffers so the writer thread blocks quickly
        a.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF, 4096)
        b.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 4096)
        a.setsockopt(
            socket.SOL_SOCKET, socket.SO_SNDTIMEO,
            __import__("struct").pack("ll", 2, 0),
        )
        peer = _Peer(a, inbound=True)
        payload = b"z" * 2048
        t0 = time.monotonic()
        # far more than SENDQ_DEPTH; b never reads
        for _ in range(_Peer.SENDQ_DEPTH * 3):
            peer.send(payload)
        elapsed = time.monotonic() - t0
        assert elapsed < 5.0, f"send() blocked the caller for {elapsed:.1f}s"
        deadline = time.monotonic() + 15
        while peer.alive and time.monotonic() < deadline:
            time.sleep(0.05)
        assert not peer.alive, "overflowing peer must be dropped"
        b.close()


class TestInboundSlots:
    def test_second_inbound_refused_with_redirect(self):
        """max_in=1: the first inbound peer is admitted, the second gets
        an ENDPOINTS redirect handout and is closed (reference:
        ConnectHandouts / doRedirect)."""
        from stellard_tpu.overlay.wire import Endpoints

        port = free_ports(1)[0]
        key = KeyPair.from_passphrase("slots-victim")
        t0 = time.monotonic()
        clock = lambda: (time.monotonic() - t0) * SPEED
        ntime = lambda: 36_000_000 + int(clock())
        ov = TcpOverlay(
            key=key, unl={key.public}, quorum=1, port=port,
            peer_addrs=[], network_time=ntime, clock=clock,
            timer_interval=0.2, idle_interval=4,
            out_desired=2, max_peers=3,  # max_in = 1
        )
        ov.start(MASTER.account_id, close_time=ntime())
        try:
            s1 = _connect(ov)
            _handshake(ov, s1, KeyPair.from_passphrase("slots-a"))
            # seed the victim's livecache so the handout is non-empty
            ov.peerfinder.livecache.insert(("10.9.9.9", 7777), 1)
            # second inbound: complete the hello (the slot check runs
            # post-handshake, once the peer is identified)
            key_b = KeyPair.from_passphrase("slots-b")
            s2 = _connect(ov)
            server_nonce = _recv_exact(s2, 32)
            nonce = _plain_nonce()
            s2.sendall(nonce)
            from stellard_tpu.overlay.tcp import HP_SESSION, PROTO_VERSION
            from stellard_tpu.utils.hashes import prefix_hash

            sh = prefix_hash(
                HP_SESSION,
                min(nonce, server_nonce) + max(nonce, server_nonce),
            )
            s2.sendall(frame(Hello(
                PROTO_VERSION, 36_000_000, key_b.public, key_b.sign(sh),
                1, b"\x00" * 32, 0,
            )))
            reader = FrameReader()
            s2.settimeout(10.0)
            got_redirect = False
            closed = False
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline and not closed:
                try:
                    data = s2.recv(65536)
                except (socket.timeout, ConnectionResetError):
                    break
                if not data:
                    closed = True
                    break
                for m in reader.feed(data):
                    if isinstance(m, Endpoints):
                        got_redirect = True
            s2.close()
            assert closed, "over-cap inbound peer must be disconnected"
            assert got_redirect, "refused peer must receive a handout"
            # slot accounting visible via the peers RPC shape
            slots = ov.slots_json()
            assert slots["in_use"] == 1 and slots["max_in"] == 1
            s1.close()
        finally:
            ov.stop()
