"""Adversarial scenario plane: fault-schedule DSL, simnet fault hooks,
segment-granular catch-up, byzantine defense evidence, degradation
reporting, and scorecard determinism."""

from __future__ import annotations

import random

import pytest

from stellard_tpu.node.inbound import SegmentCatchup, iter_segment_records
from stellard_tpu.overlay.simnet import SimNet
from stellard_tpu.overlay.wire import GetSegments, SegmentData
from stellard_tpu.testkit import (
    FaultSchedule,
    MATRIX,
    Scenario,
    build_scenario,
    run_simnet,
)
from stellard_tpu.testkit.scenarios import scenario_chaos
from stellard_tpu.testkit.workloads import TxFactory, payment_flood
from stellard_tpu.utils.hashes import sha512_half


# -- schedule DSL ----------------------------------------------------------


class TestFaultSchedule:
    def test_same_seed_same_schedule(self):
        def build(seed):
            s = FaultSchedule(seed)
            s.partition(10, {0, 1}, {2, 3}, heal_at=20)
            s.rotate_kills([0, 1, 2, 3], start=30, every=10, downtime=3,
                           count=4)
            s.link_fault(5, 0, 2, until=15, drop=0.3, jitter_steps=2)
            return s

        a, b = build(7), build(7)
        assert a.describe() == b.describe()
        assert a.digest() == b.digest()
        assert build(8).digest() != a.digest()

    def test_events_at_ordered(self):
        s = FaultSchedule(0)
        s.kill(5, 2)
        s.partition(5, {0}, {1})
        evs = s.events_at(5)
        assert [e.kind for e in evs] == ["kill", "partition"]
        assert s.events_at(6) == []

    def test_rotate_kills_bounded(self):
        s = FaultSchedule(3)
        s.rotate_kills([0, 1, 2], start=10, every=10, downtime=4, count=3)
        kills = [e for e in s.events if e.kind == "kill"]
        revives = [e for e in s.events if e.kind == "revive"]
        assert len(kills) == len(revives) == 3
        for k, r in zip(kills, revives):
            assert r.at == k.at + 4


# -- simnet fault hooks ----------------------------------------------------


class TestSimnetFaults:
    def test_drop_fault_loses_messages(self):
        net = SimNet(3, seed=1)
        net.set_link_fault(0, 1, drop=1.0)
        net.start()
        net.step(6)
        assert net.net_stats["dropped_fault"] > 0

    def test_dup_and_jitter_counted(self):
        net = SimNet(3, seed=1)
        net.set_link_fault(0, 1, dup=1.0, jitter_steps=3)
        net.start()
        net.step(8)
        assert net.net_stats["duplicated"] > 0
        assert net.net_stats["delayed"] > 0

    def test_kill_silences_and_revive_rejoins(self):
        net = SimNet(4, quorum=3, seed=2)
        net.start()
        net.run_until(lambda: net.all_validated_at_least(2), 40)
        net.kill(3)
        assert net.is_down(3)
        stalled = net.validated_seqs()[3]
        net.step(12)
        assert net.validated_seqs()[3] == stalled  # dead node frozen
        assert net.net_stats["dropped_down"] > 0
        net.revive(3)
        target = max(net.validated_seqs()) + 2
        assert net.run_until(
            lambda: net.all_validated_at_least(target), 120
        )

    def test_malformed_frame_isolated_per_source(self):
        net = SimNet(3, seed=0)
        net.start()
        v = net.validators[0]
        # garbage from node 2 must not break node 1's stream
        v.deliver(2, b"\xff\xff\xff\xff\xff\xff")
        assert v.node.defense["malformed_frame"] == 1
        net.run_until(lambda: net.all_validated_at_least(2), 40)
        assert net.validated_seqs()[0] >= 2

    def test_seeded_fault_pattern_reproducible(self):
        def run(seed):
            net = SimNet(3, seed=seed)
            net.set_link_fault(0, 1, drop=0.4, dup=0.2, jitter_steps=2)
            net.start()
            net.step(15)
            return dict(net.net_stats)

        assert run(5) == run(5)
        assert run(5) != run(6)


# -- segment records / SegmentCatchup -------------------------------------


def _record(blob: bytes, type_byte: int = 3) -> bytes:
    import struct

    key = sha512_half(blob)
    body = bytes([type_byte]) + blob
    return struct.pack("<IB", len(body), 0) + key + body


class TestSegmentRecords:
    def test_roundtrip_and_torn_tail(self):
        data = _record(b"hello") + _record(b"world" * 10)
        recs = list(iter_segment_records(data + data[:10]))
        assert [r[2] for r in recs] == [b"hello", b"world" * 10]
        assert all(sha512_half(r[2]) == r[0] for r in recs)

    def test_bad_flags_raise(self):
        data = bytearray(_record(b"x"))
        data[4] = 9  # flags byte
        with pytest.raises(ValueError):
            list(iter_segment_records(bytes(data)))


class _FakeNet:
    """Scripted transport for SegmentCatchup unit tests."""

    def __init__(self):
        self.sent = []      # (peer, msg) — delivered
        self.attempts = []  # (peer, msg) — including lost ones
        self.dead: set = set()

    def send(self, peer, msg):
        self.attempts.append((peer, msg))
        if peer in self.dead:
            return  # silently lost — the timeout path must handle it
        self.sent.append((peer, msg))


class TestSegmentCatchup:
    def _mk(self, net, peers=("a", "b", "c"), **kw):
        stored = []
        clock = [0.0]
        sc = SegmentCatchup(
            send=net.send,
            peers=lambda: list(peers),
            store=lambda tb, k, b: stored.append((tb, k, b)),
            clock=lambda: clock[0],
            request_timeout=2.0,
            backoff_base=1.0,
            backoff_max=4.0,
            seed=1,
            **kw,
        )
        return sc, stored, clock

    def test_happy_path_chunked(self):
        net = _FakeNet()
        sc, stored, clock = self._mk(net)
        sc.start()
        peer, msg = net.sent.pop()
        assert isinstance(msg, GetSegments) and msg.seg_id == -1
        seg = _record(b"n1") + _record(b"n2" * 40)
        sc.on_manifest(peer, [(0, len(seg), len(seg), False)])
        peer2, msg2 = net.sent.pop()
        assert msg2.seg_id == 0 and msg2.offset == 0
        # two chunks
        sc.on_data(peer2, SegmentData(0, len(seg), 0, seg[:30]))
        peer3, msg3 = net.sent.pop()
        assert msg3.offset == 30
        sc.on_data(peer3, SegmentData(0, len(seg), 30, seg[30:]))
        assert sc.state == "done" and not sc.active
        assert len(stored) == 2
        assert sc.counters["records"] == 2
        assert sc.counters["completed"] == 1

    def test_timeout_backoff_and_peer_switch(self):
        net = _FakeNet()
        sc, _stored, clock = self._mk(net)
        net.dead.add("a")
        sc.start()
        first_peer = net.attempts[-1][0]
        assert first_peer == "a"  # stable order: first pick
        # request times out, backs off exponentially, switches peer
        clock[0] = 2.5
        sc.tick(clock[0])
        assert sc.counters["timeouts"] == 1
        assert sc.counters["backoffs"] == 1
        n_before = len(net.attempts)
        sc.tick(clock[0] + 0.1)  # still inside backoff window
        assert len(net.attempts) == n_before
        clock[0] += 2.0  # past base backoff (1s * jitter<=1.25)
        sc.tick(clock[0])
        assert sc.counters["retries"] == 1
        assert net.attempts[-1][0] == "b"  # scored away from the dead peer
        assert sc.counters["peer_switches"] >= 1

    def test_retries_exhausted_falls_back(self):
        net = _FakeNet()
        sc, _stored, clock = self._mk(net, peers=("a",),
                                      max_retries=2)
        net.dead.add("a")
        sc.start()
        for _ in range(12):
            clock[0] += 8.0
            sc.tick(clock[0])
        assert sc.state == "fallback"
        assert not sc.active
        assert sc.counters["fallbacks"] == 1

    def test_garbage_peer_condemned_and_segment_refetched(self):
        net = _FakeNet()
        noted = []
        sc, stored, clock = self._mk(
            net, note_byzantine=lambda kind, **kw: noted.append(kind)
        )
        sc.start()
        peer, _ = net.sent.pop()
        good = _record(b"good-node")
        bad = bytearray(good)
        bad[-1] ^= 0xFF  # blob byte flip: hash mismatch
        sc.on_manifest(peer, [(0, len(good), len(good), False)])
        peer2, _ = net.sent.pop()
        sc.on_data(peer2, SegmentData(0, len(bad), 0, bytes(bad)))
        assert sc.counters["garbage_records"] == 1
        assert sc.counters["garbage_peers"] == 1
        assert "garbage_segment" in noted
        # refetched from ANOTHER peer, then completes
        peer3, msg3 = net.sent.pop()
        assert peer3 != peer2 and msg3.seg_id == 0
        sc.on_data(peer3, SegmentData(0, len(good), 0, good))
        assert sc.state == "done"
        assert len(stored) == 1

    def test_all_peers_garbage_falls_back(self):
        net = _FakeNet()
        sc, _stored, clock = self._mk(net, peers=("a", "b"))
        sc.start()
        peer, _ = net.sent.pop()
        good = _record(b"zz")
        bad = bytearray(good)
        bad[-1] ^= 1
        sc.on_manifest(peer, [(0, len(good), len(good), False)])
        for _ in range(2):
            p, _m = net.sent.pop()
            sc.on_data(p, SegmentData(0, len(bad), 0, bytes(bad)))
        assert sc.state == "fallback"
        assert sc.counters["fallbacks"] == 1

    def test_late_replies_ignored(self):
        net = _FakeNet()
        sc, _stored, _clock = self._mk(net)
        sc.start()
        peer, _ = net.sent.pop()
        sc.on_data(peer, SegmentData(3, 10, 0, b"x" * 10))
        assert sc.counters["late_replies"] == 1

    def test_hostile_total_condemns_peer_not_ram(self):
        """A peer claiming total far beyond the manifest size must be
        condemned, not buffered into an OOM."""
        net = _FakeNet()
        sc, _stored, _clock = self._mk(net)
        sc.start()
        peer, _ = net.sent.pop()
        seg = _record(b"tiny")
        sc.on_manifest(peer, [(0, len(seg), len(seg), False)])
        peer2, _ = net.sent.pop()
        sc.on_data(peer2, SegmentData(0, 1 << 50, 0, b"x" * 1024))
        assert sc.counters["garbage_peers"] == 1
        assert len(sc._buf) == 0  # nothing hostile retained
        # refetch moved to another peer
        peer3, msg3 = net.sent.pop()
        assert peer3 != peer2 and msg3.seg_id == 0

    def test_short_empty_reply_condemns_not_completes(self):
        """An empty chunk while the buffer is short of total must NOT
        count the torn buffer as a completed segment."""
        net = _FakeNet()
        sc, stored, _clock = self._mk(net)
        sc.start()
        peer, _ = net.sent.pop()
        seg = _record(b"abcdef")
        sc.on_manifest(peer, [(0, len(seg), len(seg), False)])
        peer2, _ = net.sent.pop()
        sc.on_data(peer2, SegmentData(0, len(seg), 0, b""))
        assert sc.counters["segments"] == 0
        assert sc.counters["garbage_peers"] == 1
        assert not stored

    def test_session_rearms_after_cooldown(self):
        """A fallback (or completion) must not disable the bulk path
        forever: can_start re-arms after REARM_S."""
        net = _FakeNet()
        sc, _stored, clock = self._mk(net, peers=("a",), max_retries=1)
        net.dead.add("a")
        sc.start()
        for _ in range(8):
            clock[0] += 10.0
            sc.tick(clock[0])
        assert sc.state == "fallback"
        assert not sc.can_start(clock[0])
        clock[0] += sc.REARM_S + 1
        assert sc.can_start(clock[0])
        net.dead.clear()
        assert sc.start()
        assert sc.counters["started"] == 2


# -- degradation reporting -------------------------------------------------


class TestDegradation:
    def test_quorum_loss_reports_tracking_then_recovers(self):
        net = SimNet(4, quorum=3, seed=3)
        net.start()
        net.run_until(lambda: net.all_validated_at_least(2), 40)
        v0 = net.validators[0].node
        assert v0.validator_state == "proposing"
        net.partition({0, 1}, {2, 3})
        # solo-closing without quorum validation must degrade honestly
        net.run_until(lambda: v0.degraded, 120)
        assert v0.degraded
        assert v0.validator_state == "tracking"
        assert v0.consensus_info()["validator_state"] == "tracking"
        for a in (0, 1):
            for b in (2, 3):
                net.heal_link(a, b)
        net.run_until(lambda: not v0.degraded, 200)
        assert not v0.degraded
        assert v0.validator_state == "proposing"
        assert v0.degrade_transitions >= 2


# -- scenarios end-to-end --------------------------------------------------


class TestScenarios:
    def test_matrix_names_buildable(self):
        for name in MATRIX:
            scn = build_scenario(name, seed=1)
            assert scn.name in (name, "chaos")

    def test_byzantine_scenario_defends_and_converges(self):
        card = run_simnet(build_scenario("byzantine", seed=3))
        assert card["converged"] and card["single_hash"]
        byz = card["byzantine"]
        # anti-vacuity: every hostile behavior left counter evidence
        for kind in ("bad_validation_sig", "untrusted_validation",
                     "stale_validation", "oversized_txset",
                     "malformed_frame", "duplicate_proposal",
                     "conflicting_proposal"):
            assert byz.get(kind, 0) > 0, f"{kind} never exercised"
        emitted = card["byzantine_emitted"][3]
        assert all(v > 0 for v in emitted.values())

    def test_cold_catchup_scenario(self):
        card = run_simnet(build_scenario("cold_catchup", seed=5))
        assert card["converged"] and card["single_hash"]
        cu = card["catchup"]
        assert cu["synced"], "cold node never joined the validated chain"
        sf = cu["segfetch"]
        assert sf["records"] > 0 and sf["segments"] > 0
        # the garbage server was caught and the killed server survived
        # via timeout/retry/backoff to another peer
        assert sf["garbage_peers"] >= 1
        assert sf["timeouts"] >= 1 and sf["backoffs"] >= 1
        assert sf["peer_switches"] >= 2

    def test_fee_gaming_fairness(self):
        card = run_simnet(build_scenario("fee_gaming", seed=2))
        assert card["converged"] and card["single_hash"]
        q = card["txq"]
        assert q["queued"] > 0, "queue never engaged"
        assert q["fee_order_drain"], "queue drained out of fee order"
        assert q["no_starvation"], "queued txs starved"
        assert q["remaining"] == 0

    def test_partition_kills_and_chaos_converge(self):
        # seed 7 is the regression seed: it exposed LocalTxs dropping
        # fork-reverted client txs at repair (sweep against unvalidated
        # solo-fork ledgers) and the expiry seq-jump at LCL switch —
        # full commit here pins both fixes
        for name in ("partition_kills", "chaos"):
            for seed in (7, 11):
                card = run_simnet(build_scenario(name, seed=seed))
                assert card["converged"] and card["single_hash"], name
                assert card["committed"] == card["submitted"], (
                    name, seed, card["committed"], card["submitted"],
                )

    def test_hostile_workloads_exercise_fallbacks(self):
        card = run_simnet(build_scenario("hot_account", seed=2))
        assert card["converged"] and card["single_hash"]
        # hot-account contention must actually stress the splice plane
        assert card["splice"].get("fallback", 0) > 0

    def test_scorecard_deterministic_across_runs(self):
        import json

        for name in ("byzantine", "cold_catchup"):
            scn_a = build_scenario(name, seed=42)
            scn_b = build_scenario(name, seed=42)
            a = json.dumps(run_simnet(scn_a), sort_keys=True)
            b = json.dumps(run_simnet(scn_b), sort_keys=True)
            assert a == b, f"{name}: scorecard diverged across runs"

    def test_small_custom_scenario(self):
        scn = Scenario(
            name="mini", seed=1, n_validators=3, quorum=2, steps=30,
            build_workload=lambda fac, rng, s: [
                (0, 0, tx) for tx in fac.fund_all()
            ] + payment_flood(
                fac, rng, start=4, end=24, n=10, n_validators=3
            ),
        )
        card = run_simnet(scn)
        assert card["converged"] and card["single_hash"]
        assert card["committed"] == card["submitted"] == 19

    def test_chaos_scenario_shared_across_transports(self):
        scn = scenario_chaos(seed=1)
        assert set(scn.transports) == {"simnet", "tcp"}
