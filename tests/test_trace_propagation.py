"""Cross-node trace propagation: the wire extension and the join rules.

The contract under test (PR 18 tentpole leg 1):

- the five overlay messages that move transaction causality between
  nodes (TxMessage, ProposeSet, ValidationMessage, GetSegments,
  SegmentData) round-trip a TraceContext extension at proto field 60;
- a frame WITHOUT the extension is byte-identical to the legacy wire —
  `[trace] propagate=0` (or an unsampled tx) produces exactly the bytes
  a pre-extension peer produced, pinned byte-for-byte;
- a malformed extension never drops the message (protobuf tolerance);
- sender/receiver tracers join one causal tree: wire_context() exports
  (trace, parent span id, sampled), adopt_context() links every
  subsequent local span under the foreign parent with `remote: 1`;
- span ids are node-unique (node_tag high bits), so N dumps merge with
  NO id remapping: tools/traceview.py merge_dumps + validate_merged_trace
  accept a 3-process chain as one single-rooted tree;
- the sampling decision is a pure function of (txid, rate): every node
  agrees, so a sampled tx gets its whole cross-node tree and an
  unsampled one contributes nothing anywhere.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))

from traceview import (  # noqa: E402
    merge_dumps,
    validate_chrome_trace,
    validate_merged_trace,
    validate_span_trees,
)

from stellard_tpu.node.tracer import Tracer  # noqa: E402
from stellard_tpu.overlay.proto import Encoder, first, parse  # noqa: E402
from stellard_tpu.overlay.wire import (  # noqa: E402
    TRACE_CTX_FIELD,
    GetSegments,
    MessageType,
    ProposeSet,
    SegmentData,
    TraceContext,
    TxMessage,
    ValidationMessage,
    decode_message,
    encode_message,
)

TXID = bytes(range(32))
CTX = TraceContext(trace=TXID, parent=(7 << 32) | 42, sampled=True)


def _carriers(ctx):
    """One instance of each trace-carrying message, ctx attached."""
    return [
        (MessageType.TRANSACTION,
         TxMessage(b"\x01" * 40, trace_ctx=ctx)),
        (MessageType.PROPOSE_SET,
         ProposeSet(3, 1234, b"\x02" * 32, b"\x03" * 32, b"\x04" * 33,
                    b"\x05" * 64, trace_ctx=ctx)),
        (MessageType.VALIDATION,
         ValidationMessage(b"\x06" * 50, trace_ctx=ctx)),
        (MessageType.GET_SEGMENTS,
         GetSegments(seg_id=2, offset=4096, trace_ctx=ctx)),
        (MessageType.SEGMENT_DATA,
         SegmentData(seg_id=2, total=9000, offset=4096, data=b"\x07" * 128,
                     segments=[(0, 10, 5, True)], trace_ctx=ctx)),
    ]


class TestWireRoundTrip:
    def test_ctx_round_trips_on_all_five_carriers(self):
        for mt, msg in _carriers(CTX):
            got = decode_message(int(mt), encode_message(msg))
            assert got.trace_ctx is not None, type(msg).__name__
            assert got.trace_ctx.trace == TXID
            assert got.trace_ctx.parent == CTX.parent
            assert got.trace_ctx.sampled is True

    def test_unsampled_bit_round_trips(self):
        ctx = TraceContext(trace=b"ledger-9", parent=5, sampled=False)
        got = decode_message(
            int(MessageType.TRANSACTION),
            encode_message(TxMessage(b"x", trace_ctx=ctx)),
        )
        assert got.trace_ctx.sampled is False
        assert got.trace_ctx.trace == b"ledger-9"

    def test_propagate_off_is_byte_identical_legacy_wire(self):
        """The propagate=0 pin: a message with no ctx encodes to exactly
        the bytes the pre-extension encoder produced — field 60 absent,
        and stripping a received ctx restores the legacy bytes."""
        for mt, msg in _carriers(CTX):
            bare = type(msg)(**{
                f: getattr(msg, f)
                for f in msg.__dataclass_fields__ if f != "trace_ctx"
            })
            legacy = encode_message(bare)
            assert first(parse(legacy), TRACE_CTX_FIELD) is None
            traced = encode_message(msg)
            assert traced != legacy
            assert first(parse(traced), TRACE_CTX_FIELD) is not None
            # decode-then-strip round-trips back to the legacy bytes
            got = decode_message(int(mt), traced)
            got.trace_ctx = None
            assert encode_message(got) == legacy, type(msg).__name__

    def test_malformed_ctx_never_drops_the_message(self):
        e = Encoder().blob(1, b"\xaa" * 40).varint(2, 2)
        e.blob(TRACE_CTX_FIELD, b"\xff\xff\xff")  # not a valid submessage
        got = decode_message(int(MessageType.TRANSACTION), e.data())
        assert got is not None
        assert got.blob == b"\xaa" * 40
        assert got.trace_ctx is None


class TestTracerPropagation:
    def test_wire_context_requires_propagate(self):
        t = Tracer(enabled=True, sample=1.0, propagate=False, node_tag=1)
        with t.span("verify", "tx", txid=TXID):
            pass
        assert t.wire_context(txid=TXID) is None

    def test_wire_context_requires_sampled(self):
        t = Tracer(enabled=True, sample=0.0, propagate=True, node_tag=1)
        t.instant("relay", "tx", txid=TXID)
        assert t.wire_context(txid=TXID) is None

    def test_wire_context_exports_last_span(self):
        t = Tracer(enabled=True, sample=1.0, propagate=True, node_tag=9)
        assert t.wire_context(txid=TXID) is None  # nothing recorded yet
        with t.span("verify", "tx", txid=TXID):
            pass
        ctx = t.wire_context(txid=TXID)
        assert ctx is not None
        trace_bytes, parent, sampled = ctx
        assert trace_bytes == TXID  # raw 32-byte txid, not hex
        assert parent >> 32 == 9  # node_tag rides the high bits
        assert sampled is True

    def test_adopt_links_foreign_parent_with_remote_mark(self):
        a = Tracer(enabled=True, sample=1.0, propagate=True, node_tag=1)
        b = Tracer(enabled=True, sample=1.0, propagate=True, node_tag=2)
        with a.span("submit", "tx", txid=TXID):
            pass
        tb, parent, _ = a.wire_context(txid=TXID)
        b.adopt_context(Tracer.trace_key(tb), parent)
        with b.span("relay_ingest", "tx", txid=TXID):
            pass
        ev = [e for e in b.chrome_trace()["traceEvents"]
              if e["name"] == "relay_ingest"][0]
        assert ev["args"]["parent"] == parent
        assert ev["args"]["remote"] == 1
        # span ids from different node tags never collide
        assert ev["args"]["span"] >> 32 == 2
        assert parent >> 32 == 1

    def test_adopt_noop_when_propagate_off(self):
        b = Tracer(enabled=True, sample=1.0, propagate=False, node_tag=2)
        b.adopt_context(TXID.hex(), (1 << 32) | 5)
        with b.span("verify", "tx", txid=TXID):
            pass
        ev = [e for e in b.chrome_trace()["traceEvents"]
              if e["name"] == "verify"][0]
        assert ev["args"].get("parent") is None

    def test_trace_key_inverts_wire_encoding(self):
        assert Tracer.trace_key(TXID) == TXID.hex()
        assert Tracer.trace_key(b"ledger-17") == "ledger-17"
        assert Tracer.trace_key(b"") is None
        assert Tracer.trace_key(b"\xff\xfe") is None  # undecodable

    def test_sampling_agreement_across_tracers(self):
        a = Tracer(enabled=True, sample=0.25, propagate=True, node_tag=1)
        b = Tracer(enabled=True, sample=0.25, propagate=True, node_tag=2)
        txids = [os.urandom(32) for _ in range(400)]
        decisions = [a.sampled(t) for t in txids]
        assert decisions == [b.sampled(t) for t in txids]
        assert 0 < sum(decisions) < len(txids)  # rate actually partial

    def test_single_node_dump_validates_with_remote_parent(self):
        """A node's OWN dump has an unresolvable parent for adopted
        spans — the schema validator must accept it via the remote
        mark instead of flagging a broken tree."""
        a = Tracer(enabled=True, sample=1.0, propagate=True, node_tag=1)
        b = Tracer(enabled=True, sample=1.0, propagate=True, node_tag=2)
        with a.span("submit", "tx", txid=TXID):
            pass
        tb, parent, _ = a.wire_context(txid=TXID)
        b.adopt_context(Tracer.trace_key(tb), parent)
        with b.span("relay_ingest", "tx", txid=TXID):
            pass
        dump = b.chrome_trace()
        assert validate_chrome_trace(dump) == []
        assert validate_span_trees(dump, require_stages=()) == []


def _three_node_chain():
    """origin -> relay -> follower: each hop adopts the previous hop's
    exported context, exactly as tcp.py/simnet.py ingest does."""
    nodes = [
        Tracer(enabled=True, sample=1.0, propagate=True, node_tag=i + 1)
        for i in range(3)
    ]
    with nodes[0].span("submit", "tx", txid=TXID):
        with nodes[0].span("verify", "tx", txid=TXID):
            pass
    for prev, cur in zip(nodes, nodes[1:]):
        tb, parent, _ = prev.wire_context(txid=TXID)
        cur.adopt_context(Tracer.trace_key(tb), parent)
        with cur.span("relay_ingest", "tx", txid=TXID):
            with cur.span("verify", "tx", txid=TXID):
                pass
    return nodes


class TestMergedDump:
    def test_three_process_merge_single_rooted(self):
        nodes = _three_node_chain()
        merged = merge_dumps([
            (f"node{i}", t.chrome_trace()) for i, t in enumerate(nodes)
        ])
        assert validate_chrome_trace(merged) == []
        assert validate_merged_trace(merged, min_processes=3) == []
        # the merge preserved per-node process lanes
        pids = {e["pid"] for e in merged["traceEvents"]
                if e.get("ph") != "M"}
        assert len(pids) == 3
        lanes = [e for e in merged["traceEvents"] if e.get("ph") == "M"]
        assert {e["args"]["name"] for e in lanes} == {
            "node0", "node1", "node2"
        }

    def test_merge_resolves_cross_node_parents_globally(self):
        nodes = _three_node_chain()
        merged = merge_dumps([
            (f"node{i}", t.chrome_trace()) for i, t in enumerate(nodes)
        ])
        spans = {e["args"]["span"]: e for e in merged["traceEvents"]
                 if e.get("ph") != "M"}
        unresolved = [
            e for e in spans.values()
            if e["args"].get("parent") is not None
            and e["args"]["parent"] not in spans
        ]
        assert unresolved == []

    def test_merged_validator_rejects_forest(self):
        """Anti-vacuity for the validator itself: two nodes that never
        exchanged context produce a multi-root trace, and the merged
        check must say so."""
        a = Tracer(enabled=True, sample=1.0, propagate=True, node_tag=1)
        b = Tracer(enabled=True, sample=1.0, propagate=True, node_tag=2)
        c = Tracer(enabled=True, sample=1.0, propagate=True, node_tag=3)
        for t in (a, b, c):
            with t.span("submit", "tx", txid=TXID):
                with t.span("verify", "tx", txid=TXID):
                    pass
        merged = merge_dumps([
            ("a", a.chrome_trace()), ("b", b.chrome_trace()),
            ("c", c.chrome_trace()),
        ])
        problems = validate_merged_trace(merged, min_processes=3)
        assert problems != []
        assert any("root" in p for p in problems)
