"""Tracing plane: the transaction-lifecycle span recorder.

Covers the contracts every future perf PR will argue from:
- span nesting (thread-local parent stack) and cross-thread handoff
  (begin on one thread, end on another, parent links intact);
- bounded ring: wraparound overwrites oldest, drop accounting is exact;
- sampling determinism: the record/skip decision is a pure function of
  (txid, rate), so a sampled tx gets its WHOLE tree and an unsampled
  one contributes nothing anywhere;
- Chrome trace-event schema of the `trace_dump` RPC (validated with the
  same hand-rolled validator tools/traceview.py and the tier-1 smoke
  gate use) and the causal span tree per transaction across
  submit → verify → close → persist;
- span-derived stage percentiles through the CollectorManager hook
  (statsd gauge line format);
- the overhead budget: tracing enabled must not regress close p50 by
  more than the 2% budget (interleaved best-of reps, tier-1).
"""

from __future__ import annotations

import os
import sys
import threading
import time

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))

from traceview import validate_chrome_trace, validate_span_trees  # noqa: E402

from stellard_tpu.node.config import Config  # noqa: E402
from stellard_tpu.node.metrics import CollectorManager, NullCollector  # noqa: E402
from stellard_tpu.node.node import Node  # noqa: E402
from stellard_tpu.node.tracer import Tracer, get_tracer  # noqa: E402
from stellard_tpu.protocol.formats import TxType  # noqa: E402
from stellard_tpu.protocol.keys import KeyPair  # noqa: E402
from stellard_tpu.protocol.sfields import sfAmount, sfDestination  # noqa: E402
from stellard_tpu.protocol.stamount import STAmount  # noqa: E402
from stellard_tpu.protocol.sttx import SerializedTransaction  # noqa: E402
from stellard_tpu.rpc.handlers import Context, Role, dispatch  # noqa: E402

MASTER = KeyPair.from_passphrase("masterpassphrase")
DESTS = [KeyPair.from_passphrase(f"tr-dest-{i}").account_id for i in range(4)]


def _payments(n, start_seq=1):
    txs = []
    for i in range(n):
        tx = SerializedTransaction.build(
            TxType.ttPAYMENT, MASTER.account_id, start_seq + i, 10,
            {sfAmount: STAmount.from_drops(250_000_000),
             sfDestination: DESTS[i % len(DESTS)]},
        )
        tx.sign(MASTER)
        txs.append(tx)
    return txs


def _flood(node, txs, per_ledger=50):
    """Full async pipeline submit (verify plane -> intake -> open
    ledger), closing every per_ledger; -> per-close wall ms."""
    done = threading.Semaphore(0)
    close_ms = []
    for start in range(0, len(txs), per_ledger):
        part = [
            SerializedTransaction.from_bytes(t.serialize())
            for t in txs[start:start + per_ledger]
        ]
        for tx in part:
            node.ops.submit_transaction(tx, lambda *_a: done.release())
        for _ in part:
            done.acquire()
        t0 = time.perf_counter()
        node.ops.accept_ledger()
        close_ms.append((time.perf_counter() - t0) * 1000.0)
    return close_ms


class TestRecorder:
    def test_span_nesting_links_parents(self):
        tr = Tracer(capacity=64, sample=1.0)
        with tr.span("outer", "test") as outer:
            with tr.span("inner", "test") as inner:
                assert inner.parent == outer.span_id
            with tr.span("inner2", "test") as inner2:
                assert inner2.parent == outer.span_id
        events = tr.chrome_trace()["traceEvents"]
        by_name = {e["name"]: e for e in events}
        assert by_name["inner"]["args"]["parent"] == by_name["outer"]["args"]["span"]
        assert by_name["outer"]["args"].get("parent") is None
        # children recorded before the parent ends, all phases complete
        assert all(e["ph"] == "X" for e in events)

    def test_cross_thread_handoff(self):
        """begin() on one thread, end() on another: duration measured
        across the handoff, parent chain intact."""
        tr = Tracer(capacity=64, sample=1.0)
        tok = tr.begin("handoff", "test", txid=b"\x01" * 32)
        child_ids = []

        def other():
            child = tr.begin("child", "test", txid=b"\x01" * 32, parent=tok)
            child_ids.append(child.span_id)
            tr.end(child)
            tr.end(tok, outcome="done")

        t = threading.Thread(target=other)
        t.start()
        t.join()
        events = tr.chrome_trace()["traceEvents"]
        by_name = {e["name"]: e for e in events}
        assert by_name["child"]["args"]["parent"] == tok.span_id
        assert by_name["handoff"]["args"]["outcome"] == "done"
        assert by_name["handoff"]["args"]["trace"] == ("01" * 32)

    def test_end_accepts_none_token(self):
        """Callers never branch on the sampling decision: end(None) is a
        no-op (the begin() returned None for an unsampled tx)."""
        tr = Tracer(capacity=64, sample=0.0)
        tok = tr.begin("skipped", "test", txid=b"\x02" * 32)
        assert tok is None
        tr.end(tok)  # must not raise
        assert tr.chrome_trace()["traceEvents"] == []

    def test_ring_wraparound(self):
        tr = Tracer(capacity=16, sample=1.0)
        for i in range(40):
            tr.instant(f"ev-{i}", "test")
        j = tr.get_json()
        assert j["recorded"] == 40
        assert j["buffered"] == 16
        assert j["dropped"] == 24
        events = tr.chrome_trace()["traceEvents"]
        assert len(events) == 16
        # oldest overwritten: exactly the last 16, in order
        assert [e["name"] for e in events] == [f"ev-{i}" for i in range(24, 40)]

    def test_sampling_determinism(self):
        txids = [bytes([i]) * 32 for i in range(200)]
        a = Tracer(sample=0.25)
        b = Tracer(sample=0.25)
        va = [a.sampled(t) for t in txids]
        vb = [b.sampled(t) for t in txids]
        assert va == vb, "decision must be a pure function of (txid, rate)"
        assert any(va) and not all(va)
        # rate edges
        assert all(Tracer(sample=1.0).sampled(t) for t in txids)
        assert not any(Tracer(sample=0.0).sampled(t) for t in txids)
        # a sampled-out tx records nothing through any path
        t_out = next(t for t, v in zip(txids, va) if not v)
        a.instant("close.tx", "close", txid=t_out)
        with a.span("open.apply", "apply", txid=t_out):
            pass
        assert a.chrome_trace()["traceEvents"] == []
        # disabled tracer records nothing at all
        off = Tracer(enabled=False)
        off.instant("x", "test")
        assert not off.sampled(b"\x03" * 32)
        assert off.chrome_trace()["traceEvents"] == []

    def test_ledger_spans_bypass_sampling(self):
        tr = Tracer(sample=0.0)
        t0 = time.perf_counter()
        tr.complete("close.total", "close", t0, t0 + 0.01, seq=7)
        events = tr.chrome_trace()["traceEvents"]
        assert len(events) == 1
        assert events[0]["args"]["trace"] == "ledger-7"

    def test_stage_hist_and_statsd_hook(self):
        """Span durations feed the per-stage LatencyHist; the collector
        hook ships p50/p90/p99 as statsd gauges."""
        tr = Tracer(sample=1.0)
        t0 = time.perf_counter()
        for ms in (2.0, 4.0, 6.0, 8.0, 100.0):
            tr.complete("close.apply", "close", t0, t0 + ms / 1000.0, seq=1)
        hook = tr.statsd_hook()
        assert hook["close.apply.p50_ms"] > 0
        assert hook["close.apply.p99_ms"] >= hook["close.apply.p50_ms"]
        mgr = CollectorManager(NullCollector())
        mgr.hook("trace", tr.statsd_hook)
        lines = mgr.flush_once()
        assert any(
            line.startswith("trace.close.apply.p50_ms:") and line.endswith("|g")
            for line in lines
        )

    def test_reset(self):
        tr = Tracer(capacity=32, sample=1.0)
        tr.instant("a", "test")
        tr.reset()
        j = tr.get_json()
        assert j["recorded"] == 0 and j["stages"] == {}


class TestConfig:
    def test_trace_section_parses(self):
        cfg = Config.from_ini("[trace]\nenabled=0\ncapacity=512\nsample=0.5\n")
        assert cfg.trace_enabled is False
        assert cfg.trace_capacity == 512
        assert cfg.trace_sample == 0.5
        # defaults: sampled-on
        d = Config()
        assert d.trace_enabled is True
        assert 0.0 < d.trace_sample <= 1.0
        tr = Tracer.from_config(cfg)
        assert tr.enabled is False and tr.capacity == 512

    def test_default_tracer_exists(self):
        assert get_tracer() is get_tracer()


class TestEndToEnd:
    def test_trace_dump_schema_and_span_trees(self):
        """A traced flood produces a valid Chrome trace whose every tx
        trace spans submit, verify, close, and persist stages with
        resolvable parent links — via the real RPC handler."""
        node = Node(Config(trace_sample=1.0)).setup()
        try:
            _flood(node, _payments(40), per_ledger=20)
            assert node.close_pipeline.flush(timeout=60)
            dump = dispatch(Context(node, {}), "trace_dump")
            assert validate_chrome_trace(dump) == []
            assert validate_span_trees(dump) == []
            events = dump["traceEvents"]
            names = {e["name"] for e in events}
            # the pipeline's load-bearing stages all surface
            for expected in ("submit", "verify.wait", "process",
                             "open.apply", "verify.batch", "close.apply",
                             "close.total", "close.tx", "persist.nodestore",
                             "persist.txdb", "persist.clf", "persist.tx",
                             "jobq.jtTRANSACTION.run"):
                assert expected in names, f"missing {expected}"
            # per-tx causal chain: submit -> verify.wait -> process
            tx_traces = {
                (e.get("args") or {}).get("trace")
                for e in events
                if len((e.get("args") or {}).get("trace") or "") == 64
            }
            assert len(tx_traces) == 40
        finally:
            node.stop()

    def test_trace_status_and_counts_surface(self):
        node = Node(Config(trace_sample=1.0)).setup()
        try:
            _flood(node, _payments(10), per_ledger=10)
            assert node.close_pipeline.flush(timeout=60)
            status = dispatch(Context(node, {}), "trace_status")["trace"]
            assert status["enabled"] is True
            assert status["recorded"] > 0
            assert "close.total" in status["stages"]
            assert status["stages"]["close.total"]["count"] == 1
            # timeline block in server_state + get_counts (ADMIN)
            state = dispatch(Context(node, {}), "server_state")["state"]
            assert any(
                ev["name"] == "close.total" for ev in state["trace"]["timeline"]
            )
            counts = dispatch(Context(node, {}), "get_counts")
            assert counts["trace"]["recorded"] > 0
            # GUEST server_state gets aggregate status only — the
            # timeline carries txids/peer prefixes and must not leak
            # past the ADMIN gate trace_status/trace_dump sit behind
            guest = dispatch(
                Context(node, {}, role=Role.GUEST), "server_state"
            )["state"]
            assert "timeline" not in guest["trace"]
            assert guest["trace"]["recorded"] > 0
            assert "error" in dispatch(
                Context(node, {}, role=Role.GUEST), "trace_dump"
            )
            # close-stage percentiles still surface (now LatencyHist-fed)
            assert "apply_p50_ms" in state["delta_replay"]
        finally:
            node.stop()

    def test_trace_dump_reset_windows(self):
        node = Node(Config(trace_sample=1.0)).setup()
        try:
            _flood(node, _payments(5), per_ledger=5)
            dump = dispatch(Context(node, {"reset": True}), "trace_dump")
            assert len(dump["traceEvents"]) > 0
            dump2 = dispatch(Context(node, {}), "trace_dump")
            # only events recorded after the reset (possibly none)
            assert len(dump2["traceEvents"]) < len(dump["traceEvents"])
        finally:
            node.stop()

    def test_sampling_prunes_whole_trees(self):
        """At a fractional rate, an unsampled tx appears NOWHERE (no
        orphan stage events), and sampled txs keep complete trees."""
        node = Node(Config(trace_sample=0.25)).setup()
        try:
            txs = _payments(60)
            _flood(node, txs, per_ledger=30)
            assert node.close_pipeline.flush(timeout=60)
            dump = dispatch(Context(node, {}), "trace_dump")
            tracer = node.tracer
            sampled = {t.txid().hex() for t in txs if tracer.sampled(t.txid())}
            assert 0 < len(sampled) < 60
            seen = {}
            for ev in dump["traceEvents"]:
                trace = (ev.get("args") or {}).get("trace")
                if trace and len(trace) == 64:
                    seen.setdefault(trace, set()).add(ev.get("cat"))
            assert set(seen) == sampled
            for cats in seen.values():
                assert {"submit", "verify", "close", "persist"} <= cats
        finally:
            node.stop()


class TestOverhead:
    def test_close_p50_overhead_budget(self):
        """Tracing enabled (default sampled-on) must cost < 2% close p50
        vs tracing disabled. Interleaved best-of-3 reps (the PERF.md
        convention) with a small absolute floor so a noisy CI box can't
        flake a sub-millisecond delta. The incremental seal's background
        drainer is off in BOTH modes: it is orthogonal to tracing and
        its thread adds scheduling variance to the now-~10ms closes that
        best-of-3 cannot always average out."""
        txs = _payments(300)
        best = {"on": float("inf"), "off": float("inf")}
        for _rep in range(5):
            for mode, enabled in (("off", False), ("on", True)):
                node = Node(Config(trace_enabled=enabled,
                                   tree_drain_batch=0)).setup()
                try:
                    close_ms = sorted(_flood(node, txs, per_ledger=100))
                    p50 = close_ms[len(close_ms) // 2]
                    best[mode] = min(best[mode], p50)
                finally:
                    node.stop()
        # floor 2.5ms: the same ABSOLUTE gate this test enforced when
        # closes were ~76ms (2% x 76 + 1.0) — the batched commit plane
        # cut close p50 ~4x, and a pure-relative budget at a ~12ms
        # denominator sits below this box's per-rep scheduling noise
        assert best["on"] <= best["off"] * 1.02 + 2.5, (
            f"tracing overhead over budget: enabled p50 {best['on']:.2f}ms "
            f"vs disabled {best['off']:.2f}ms"
        )


class TestValidator:
    def test_schema_validator_catches_breakage(self):
        assert validate_chrome_trace({"traceEvents": []}) == []
        assert validate_chrome_trace([]) != []
        assert validate_chrome_trace({}) != []
        bad_phase = {"traceEvents": [
            {"name": "x", "ph": "Z", "ts": 0, "pid": 1, "tid": 1}
        ]}
        assert validate_chrome_trace(bad_phase) != []
        missing_dur = {"traceEvents": [
            {"name": "x", "ph": "X", "ts": 0, "pid": 1, "tid": 1}
        ]}
        assert validate_chrome_trace(missing_dur) != []
        neg_ts = {"traceEvents": [
            {"name": "x", "ph": "i", "s": "t", "ts": -5, "pid": 1, "tid": 1}
        ]}
        assert validate_chrome_trace(neg_ts) != []
        ok = {"traceEvents": [
            {"name": "x", "ph": "X", "ts": 1, "dur": 2, "pid": 1, "tid": 1,
             "cat": "c", "args": {"trace": "ab"}},
            {"name": "y", "ph": "i", "s": "t", "ts": 1, "pid": 1, "tid": 1},
        ]}
        assert validate_chrome_trace(ok) == []

    def test_span_tree_validator_catches_breakage(self):
        txid = "ab" * 32
        complete = {"traceEvents": [
            {"name": "submit", "cat": "submit", "ph": "X", "ts": 0, "dur": 1,
             "pid": 1, "tid": 1, "args": {"trace": txid, "span": 1}},
            {"name": "verify.wait", "cat": "verify", "ph": "X", "ts": 1,
             "dur": 1, "pid": 1, "tid": 1,
             "args": {"trace": txid, "span": 2, "parent": 1}},
            {"name": "close.tx", "cat": "close", "ph": "i", "s": "t", "ts": 2,
             "pid": 1, "tid": 1, "args": {"trace": txid, "span": 3}},
            {"name": "persist.tx", "cat": "persist", "ph": "i", "s": "t",
             "ts": 3, "pid": 1, "tid": 1, "args": {"trace": txid, "span": 4}},
        ]}
        assert validate_span_trees(complete) == []
        # drop the persist stage -> broken tree reported
        partial = {"traceEvents": complete["traceEvents"][:-1]}
        assert any("persist" in p for p in validate_span_trees(partial))
        # dangling parent reference reported
        dangling = {"traceEvents": [
            dict(complete["traceEvents"][0],
                 args={"trace": txid, "span": 9, "parent": 777}),
        ]}
        probs = validate_span_trees(dangling)
        assert any("parent" in p for p in probs)
        assert validate_span_trees({"traceEvents": []}) != []
