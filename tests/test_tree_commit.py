"""Batched state-tree commit plane: the byte contracts.

Four surfaces, each pinned against the serial/per-key ground truth:

- ``SHAMap.bulk_update`` (sorted one-pass delta merge, C + Python
  implementations) must be byte-identical to per-key
  ``set_item``/``del_item`` for ANY final key->value map — randomized
  mixed streams, adversarial shared-prefix keys, delete-driven
  collapse, structural sharing across snapshots;
- the flat-buffer node encoder (native ``pack_nodes`` + Python
  fallback) must produce exactly the per-node prefix-format blobs, and
  flush-through-the-encoder must store the same bytes the old per-node
  serializer did;
- the incremental seal (building tree + background drain + root
  adoption) must close byte-identically to the full seal across
  adversarial deletes and a mid-stream snapshot;
- the hash router's ``min_device_nodes`` floor must keep small batches
  off the device without disturbing measured routing above it.
"""

from __future__ import annotations

import hashlib
import random

import pytest

from stellard_tpu.crypto.backend import (
    BatchHasher,
    CpuHasher,
    WatchdogHasher,
    _HashCostModel,
)
from stellard_tpu.nodestore import NodeObjectType, make_database
from stellard_tpu.state.shamap import (
    EMPTY_INNER,
    Inner,
    Leaf,
    SHAMap,
    SHAMapItem,
    TNType,
    ZERO256,
    _bulk_merge,
    _collect_unhashed,
    _encode_nodes_py,
    _resolve_native_merge,
    _resolve_native_pack,
    encode_nodes,
    inner_node_cache,
    serialize_node_prefix,
)


def h(x) -> bytes:
    return hashlib.sha256(repr(x).encode()).digest()


def shared_prefix_key(base: bytes, nibbles: int, salt) -> bytes:
    """A key sharing `nibbles` leading nibbles with `base` (adversarial
    deep leaf-collision chains)."""
    raw = bytearray(h(("sp", salt)))
    for i in range(nibbles):
        b = base[i // 2]
        if i % 2 == 0:
            raw[i // 2] = (b & 0xF0) | (raw[i // 2] & 0x0F)
        else:
            raw[i // 2] = (raw[i // 2] & 0xF0) | (b & 0x0F)
    return bytes(raw)


def apply_per_key(m: SHAMap, sets, deletes) -> None:
    for item in sets:
        m.set_item(SHAMapItem(item.tag, item.data))
    for k in deletes:
        m.del_item(k)


def python_bulk_update(m: SHAMap, sets, deletes) -> None:
    """bulk_update forced through the pure-Python merge (the
    toolchain-less fallback), regardless of the native binding."""
    ops = {}
    for item in sets:
        ops[item.tag] = Leaf(item, m.leaf_type)
    for k in deletes:
        ops[k] = None
    sorted_ops = sorted(ops.items())
    dels = [0] * (len(sorted_ops) + 1)
    for i, (_k, leaf) in enumerate(sorted_ops):
        dels[i + 1] = dels[i] + (leaf is None)
    root = _bulk_merge(m.root, sorted_ops, 0, len(sorted_ops), 0, dels)
    m.root = m._normalize_root(root)


class TestBulkUpdateDifferential:
    """Randomized set/delete streams: bulk (C and Python) vs per-key."""

    def test_randomized_streams_byte_identical(self):
        rng = random.Random(1234)
        for trial in range(25):
            keys = [h((trial, i)) for i in range(rng.randrange(2, 120))]
            # adversarial: keys sharing deep nibble prefixes
            for i in range(len(keys) // 3):
                keys.append(
                    shared_prefix_key(keys[i], rng.randrange(1, 12),
                                      (trial, i))
                )
            m_ref, m_c, m_py = SHAMap(), SHAMap(), SHAMap()
            live: set = set()
            for round_ in range(4):
                chosen = {}
                for k in keys:
                    r = rng.random()
                    if r < 0.5:
                        chosen[k] = "set"
                    elif r < 0.7 and k in live:
                        chosen[k] = "del"
                sets, dels = [], []
                for k, op in chosen.items():
                    if op == "set":
                        data = h((trial, round_, k))[: rng.randrange(1, 32)]
                        sets.append(SHAMapItem(k, data or b"x"))
                    else:
                        dels.append(k)
                live |= {s.tag for s in sets}
                live -= set(dels)
                apply_per_key(m_ref, sets, dels)
                m_c.bulk_update(sets, dels)
                python_bulk_update(m_py, sets, dels)
                assert m_c.get_hash() == m_ref.get_hash()
                assert m_py.get_hash() == m_ref.get_hash()
                assert len(m_c) == len(m_ref) == len(m_py)

    def test_empty_inner_collapse_and_delete_all(self):
        keys = [h(("col", i)) for i in range(40)]
        m_ref, m_bulk = SHAMap(), SHAMap()
        sets = [SHAMapItem(k, b"v") for k in keys]
        apply_per_key(m_ref, sets, [])
        m_bulk.bulk_update(sets)
        # delete down to a single survivor: every transient inner must
        # fold up identically
        survivors = keys[:1]
        dels = keys[1:]
        apply_per_key(m_ref, [], dels)
        m_bulk.bulk_update([], dels)
        assert m_bulk.get_hash() == m_ref.get_hash()
        assert [i.tag for i in m_bulk.items()] == survivors
        # and to empty
        m_ref.del_item(survivors[0])
        m_bulk.bulk_update([], survivors)
        assert m_bulk.get_hash() == m_ref.get_hash() == ZERO256
        assert m_bulk.root is EMPTY_INNER

    def test_missing_delete_raises_keyerror(self):
        m = SHAMap()
        m.bulk_update([SHAMapItem(h(1), b"a")])
        with pytest.raises(KeyError):
            m.bulk_update([], [h(2)])
        # missing_ok drops it instead (the compacted create-then-delete)
        before = m.get_hash()
        m.bulk_update([], [h(2)], missing_ok=True)
        assert m.get_hash() == before

    def test_set_and_delete_same_key_rejected(self):
        m = SHAMap()
        m.bulk_update([SHAMapItem(h(1), b"a")])
        with pytest.raises(ValueError):
            m.bulk_update([SHAMapItem(h(1), b"b")], [h(1)])

    def test_duplicate_sets_last_wins(self):
        m_ref, m_bulk = SHAMap(), SHAMap()
        m_ref.set_item(SHAMapItem(h(1), b"first"))
        m_ref.set_item(SHAMapItem(h(1), b"second"))
        m_bulk.bulk_update(
            [SHAMapItem(h(1), b"first"), SHAMapItem(h(1), b"second")]
        )
        assert m_bulk.get_hash() == m_ref.get_hash()

    def test_snapshot_structural_sharing_preserved(self):
        base = SHAMap()
        base.bulk_update([SHAMapItem(h(("s", i)), b"v" * 20)
                          for i in range(200)])
        base.get_hash()
        snap = base.snapshot()
        snap_hash = snap.get_hash()
        snap_root = snap.root
        # a delta touching a few branches must leave the snapshot frozen
        # and SHARE every untouched branch by object identity
        sets = [SHAMapItem(h(("s", i)), b"w" * 25) for i in range(10)]
        base.bulk_update(sets, [h(("s", 42))])
        assert snap.get_hash() == snap_hash
        assert snap.root is snap_root
        dirty = {s.tag[0] >> 4 for s in sets} | {h(("s", 42))[0] >> 4}
        shared = untouched = 0
        for b in range(16):
            if b in dirty:
                continue
            untouched += 1
            if base.root.children[b] is snap_root.children[b]:
                shared += 1
        assert untouched > 0 and shared == untouched

    def test_mid_stream_snapshot_stays_frozen(self):
        m = SHAMap()
        hashes = []
        snaps = []
        rng = random.Random(7)
        live = []
        for round_ in range(6):
            sets = [SHAMapItem(h(("m", round_, i)), bytes([round_]) * 9)
                    for i in range(30)]
            dels = [live.pop(rng.randrange(len(live)))
                    for _ in range(min(5, len(live)))]
            live += [s.tag for s in sets]
            m.bulk_update(sets, dels)
            snaps.append(m.snapshot())
            hashes.append(m.get_hash())
        for snap, expect in zip(snaps, hashes):
            assert snap.get_hash() == expect


class TestFlatBufferEncoder:
    def _tree(self, n=150, leaf_type=TNType.ACCOUNT_STATE):
        m = SHAMap(leaf_type)
        for i in range(n):
            m.set_item(SHAMapItem(h(("e", i)), h(("d", i)) * 2))
        return m

    def test_encoder_matches_per_node_serializer(self):
        m = self._tree()
        nodes = [n for lv in _collect_unhashed(m.root) for n in lv]
        m.get_hash()
        buf, offsets = encode_nodes(nodes)
        assert len(offsets) == len(nodes) + 1
        for i, node in enumerate(nodes):
            assert buf[offsets[i]:offsets[i + 1]] == \
                serialize_node_prefix(node)

    def test_native_and_python_encoders_agree(self):
        if _resolve_native_pack() is None:
            pytest.skip("native pack unavailable")
        for leaf_type in (TNType.ACCOUNT_STATE, TNType.TX_MD, TNType.TX_NM):
            m = self._tree(80, leaf_type)
            nodes = [n for lv in _collect_unhashed(m.root) for n in lv]
            m.get_hash()
            assert encode_nodes(nodes) == _encode_nodes_py(nodes)

    def test_packed_hashing_matches_default(self):
        m1, m2 = self._tree(), self._tree()
        m2.hash_batch = CpuHasher()  # has hash_packed -> flat-buffer path
        assert m1.get_hash() == m2.get_hash()

    def test_flush_via_encoder_byte_identical_and_batched(self):
        m = self._tree()
        stored: dict[bytes, bytes] = {}
        batches: list[int] = []

        def store_many(pairs):
            batches.append(len(pairs))
            stored.update(pairs)

        n = m.flush(lambda hh, d: stored.__setitem__(hh, d),
                    store_many=store_many)
        assert n == len(stored) and batches  # batch sink actually used
        # every stored blob equals the old per-node serialization and
        # round-trips from_store
        for node_hash, blob in stored.items():
            from stellard_tpu.utils.hashes import sha512_half

            assert sha512_half(blob) == node_hash
        rebuilt = SHAMap.from_store(m.get_hash(), stored.get,
                                    use_cache=False)
        assert rebuilt.get_hash() == m.get_hash()

    def test_flush_known_set_incremental(self):
        m = self._tree()
        writes: list = []
        known: set = set()
        assert m.flush(lambda hh, d: writes.append(hh), known) > 0
        assert m.flush(lambda hh, d: writes.append(hh), known) == 0

    def test_failed_flush_stays_retryable(self):
        """A store that raises must NOT leave the known set claiming
        nodes the backend never saw (review regression: known was
        populated during the visit, before any store ran)."""
        m = self._tree()
        known: set = set()

        def broken_store(hh, d):
            raise RuntimeError("nodestore writer failed")

        with pytest.raises(RuntimeError):
            m.flush(broken_store, known)
        assert not known  # nothing persisted -> nothing marked flushed
        stored: dict = {}
        assert m.flush(lambda hh, d: stored.__setitem__(hh, d), known) > 0
        rebuilt = SHAMap.from_store(m.get_hash(), stored.get,
                                    use_cache=False)
        assert rebuilt.get_hash() == m.get_hash()

    def test_database_store_many_round_trip(self):
        db = make_database(type="memory")
        m = self._tree()
        m.flush(db.store_fn(NodeObjectType.ACCOUNT_NODE), db.flushed,
                store_many=db.store_many_fn(NodeObjectType.ACCOUNT_NODE))
        db.sync()
        rebuilt = SHAMap.from_store(
            m.get_hash(),
            lambda hh: (db.fetch(hh).data if db.fetch(hh) else None),
            use_cache=False,
        )
        assert rebuilt.get_hash() == m.get_hash()


class TestFromStoreCache:
    def test_hits_counted_and_bytes_identical(self):
        cache = inner_node_cache()
        before_puts = len(cache)
        m = SHAMap()
        for i in range(120):
            m.set_item(SHAMapItem(h(("c", i)), b"payload" * 3))
        stored: dict[bytes, bytes] = {}
        m.flush(lambda hh, d: stored.__setitem__(hh, d))
        root = m.get_hash()

        first = SHAMap.from_store(root, stored.get)
        assert len(cache) > before_puts  # inners memoized
        h0, m0 = cache.hits, cache.misses
        fetches: list = []

        def counting_fetch(hh):
            fetches.append(hh)
            return stored.get(hh)

        second = SHAMap.from_store(root, counting_fetch)
        assert cache.hits > h0  # shared inners served from the memo
        assert not fetches  # the root inner hit covers the whole tree
        assert first.get_hash() == second.get_hash() == root
        assert sorted(i.tag for i in second.items()) == \
            sorted(i.tag for i in first.items())

    def test_cache_opt_out(self):
        m = SHAMap()
        for i in range(40):
            m.set_item(SHAMapItem(h(("o", i)), b"x" * 10))
        stored: dict[bytes, bytes] = {}
        m.flush(lambda hh, d: stored.__setitem__(hh, d))
        SHAMap.from_store(m.get_hash(), stored.get)  # populate
        fetches: list = []

        def counting_fetch(hh):
            fetches.append(hh)
            return stored.get(hh)

        SHAMap.from_store(m.get_hash(), counting_fetch, use_cache=False)
        assert fetches  # opt-out really bypasses the memo


class TestMinDeviceNodesFloor:
    def test_cost_model_floor_blocks_exploration(self):
        m = _HashCostModel(reexplore_every=8, min_device_nodes=64)
        assert not m.use_device(1)
        assert not m.use_device(63)  # below floor: never explore
        assert m.use_device(64)  # at floor: unmeasured -> explore
        assert m.use_device(4096)

    def test_floor_zero_keeps_old_behavior(self):
        m = _HashCostModel(reexplore_every=8)
        assert m.use_device(1)  # unmeasured: explore, as before

    class _Counting(BatchHasher):
        name = "fake-dev"

        def __init__(self):
            self.flat_calls = 0
            self.tree_calls = 0
            self.device_nodes = 0
            self.host_nodes = 0

        def prefix_hash_batch(self, prefixes, payloads):
            self.flat_calls += 1
            return CpuHasher().prefix_hash_batch(prefixes, payloads)

        def hash_tree(self, root):
            self.tree_calls += 1
            from stellard_tpu.state.shamap import compute_hashes

            return compute_hashes(root, CpuHasher())

    def test_watchdog_floor_routes_small_batches_to_host(self):
        dev, host = self._Counting(), self._Counting()
        wd = WatchdogHasher(dev, host, first_timeout=30, warm_timeout=30,
                            min_device_nodes=16)
        wd.prefix_hash_batch([0x1234] * 4, [b"x" * 20] * 4)
        assert dev.flat_calls == 0 and host.flat_calls == 1
        wd.prefix_hash_batch([0x1234] * 32, [b"x" * 20] * 32)
        assert dev.flat_calls == 1  # above the floor: explored

    def test_watchdog_tree_hint_floor(self):
        dev, host = self._Counting(), self._Counting()
        wd = WatchdogHasher(dev, host, first_timeout=30, warm_timeout=30,
                            min_device_nodes=16)
        def mk():
            mm = SHAMap()
            for i in range(10):
                mm.set_item(SHAMapItem(h(("t", i)), b"y" * 12))
            return mm

        expect = mk().get_hash()
        m = mk()  # fresh nodes: nothing pre-hashed
        # small declared dirty set: host level-batcher, not the device
        n = wd.hash_tree(m.root, hint_nodes=4)
        assert n > 0 and dev.tree_calls == 0
        assert m.root._hash == expect
        # a big hint reaches the device tree pipeline
        m2 = SHAMap()
        for i in range(10):
            m2.set_item(SHAMapItem(h(("t2", i)), b"z" * 12))
        wd.hash_tree(m2.root, hint_nodes=400)
        assert dev.tree_calls == 1

    def test_watchdog_routing_snapshot(self):
        dev, host = self._Counting(), self._Counting()
        wd = WatchdogHasher(dev, host, first_timeout=30,
                            min_device_nodes=16)
        wd.prefix_hash_batch([0x1234] * 2, [b"x"] * 2)
        snap = wd.get_json()
        assert snap["min_device_nodes"] == 16
        assert snap["flat_model"]["min_device_nodes"] == 16
        assert "buckets" in snap["flat_model"]


class TestIncrementalSealByteIdentity:
    """Full close-path identity: incremental seal vs full seal vs serial
    re-apply, over workloads with creates, overwrites and DELETES
    (offer cancels), plus a mid-stream snapshot consumer."""

    def _run(self, incremental, delta_replay=True, drain_batch=8):
        from stellard_tpu.engine.engine import TxParams
        from stellard_tpu.node.ledgermaster import LedgerMaster
        from stellard_tpu.protocol.formats import TxType
        from stellard_tpu.protocol.keys import KeyPair
        from stellard_tpu.protocol.sfields import (
            sfAmount,
            sfDestination,
            sfLimitAmount,
            sfOfferSequence,
            sfTakerGets,
            sfTakerPays,
        )
        from stellard_tpu.protocol.stamount import STAmount
        from stellard_tpu.protocol.sttx import SerializedTransaction

        master = KeyPair.from_passphrase("masterpassphrase")
        gw = KeyPair.from_passphrase("tree-gw")
        USD = b"USD" + b"\x00" * 17
        OPEN = TxParams.OPEN_LEDGER | TxParams.RETRY

        def build(tx_type, kp, seq, fields):
            tx = SerializedTransaction.build(
                tx_type, kp.account_id, seq, 10, fields
            )
            tx.sign(kp)
            return SerializedTransaction.from_bytes(tx.serialize())

        lm = LedgerMaster()
        lm.delta_replay = delta_replay
        lm.incremental_seal = incremental
        lm.seal_drain_batch = drain_batch
        lm.start_new_ledger(master.account_id, close_time=1000)
        try:
            hashes = []
            # phase 1: fund the gateway + fan-out payments (creates)
            seq = 1
            phase = [build(TxType.ttPAYMENT, master, seq,
                           {sfAmount: STAmount.from_drops(1_000_000_000),
                            sfDestination: gw.account_id})]
            seq += 1
            for i in range(12):
                dest = KeyPair.from_passphrase(f"tree-d{i}").account_id
                phase.append(build(
                    TxType.ttPAYMENT, master, seq,
                    {sfAmount: STAmount.from_drops(250_000_000),
                     sfDestination: dest},
                ))
                seq += 1
            for tx in phase:
                lm.do_transaction(tx, OPEN)
            closed, _ = lm.close_and_advance(2000, 30)
            hashes.append(closed.hash())
            snap = closed.snapshot()  # mid-stream snapshot consumer
            snap_hash = snap.hash()
            # phase 2: offers created then cancelled (adversarial
            # deletes: created-then-deleted entries inside one close)
            phase = []
            gw_seq = 1
            for i in range(4):
                phase.append(build(
                    TxType.ttOFFER_CREATE, gw, gw_seq,
                    {sfTakerPays: STAmount.from_drops((50 + i) * 1_000_000),
                     sfTakerGets: STAmount.from_iou(
                         USD, gw.account_id, 100, 0)},
                ))
                gw_seq += 1
            for i in range(2):
                phase.append(build(
                    TxType.ttOFFER_CANCEL, gw, gw_seq,
                    {sfOfferSequence: 1 + i},
                ))
                gw_seq += 1
            for tx in phase:
                lm.do_transaction(tx, OPEN)
            closed, _ = lm.close_and_advance(2030, 30)
            hashes.append(closed.hash())
            # phase 3: overwrites of hot entries
            phase = [build(TxType.ttPAYMENT, master, seq + i,
                           {sfAmount: STAmount.from_drops(1_000_000),
                            sfDestination: gw.account_id})
                     for i in range(10)]
            for tx in phase:
                lm.do_transaction(tx, OPEN)
            closed, _ = lm.close_and_advance(2060, 30)
            hashes.append(closed.hash())
            assert snap.hash() == snap_hash  # snapshot stayed frozen
            return hashes, lm.tree_json()
        finally:
            lm.stop_seal_drainer()

    def test_incremental_matches_full_and_serial(self):
        h_incr, tree = self._run(incremental=True)
        h_full, _ = self._run(incremental=False)
        h_serial, _ = self._run(incremental=False, delta_replay=False)
        assert h_incr == h_full == h_serial
        # the incremental run actually engaged (honesty check)
        assert tree["seal_adopted"] >= 1
        assert tree["bulk_merges"] >= 1

    def test_kill_switch_off_never_arms(self):
        _hashes, tree = self._run(incremental=False)
        assert tree["seal_adopted"] == 0
        assert tree["drains"] == 0

    def test_drain_batch_zero_disables_drains_not_adoption(self):
        """[tree] drain_batch=0: no background drain thread (and no
        busy-loop — review finding), but folding + root adoption still
        produce byte-identical closes."""
        h0, tree0 = self._run(incremental=True, drain_batch=0)
        h1, _ = self._run(incremental=False)
        assert h0 == h1
        assert tree0["drains"] == 0
        assert tree0["seal_adopted"] >= 1


class TestCompactedCreateThenDelete:
    """A tx that creates AND deletes the same key compacts its record to
    a bare delete; against a state that never held the key the splice
    must net it to NOTHING (serial set_item/del_item parity) — not
    crash the close flush with a KeyError (review regression)."""

    def _splice_record(self, writes_script):
        """Drive one synthetic SpecRecord through a real CloseReplay on
        a fresh chain; returns (ledger, ok)."""
        from stellard_tpu.engine.deltareplay import (
            CloseReplay,
            SpecRecord,
            SpecState,
        )
        from stellard_tpu.engine.engine import TransactionEngine
        from stellard_tpu.node.ledgermaster import LedgerMaster
        from stellard_tpu.protocol.formats import TxType
        from stellard_tpu.protocol.keys import KeyPair
        from stellard_tpu.protocol.sfields import (
            sfAmount,
            sfDestination,
            sfTransactionIndex,
        )
        from stellard_tpu.protocol.stamount import STAmount
        from stellard_tpu.protocol.stobject import STObject
        from stellard_tpu.protocol.sttx import SerializedTransaction
        from stellard_tpu.protocol.ter import TER

        master = KeyPair.from_passphrase("masterpassphrase")
        lm = LedgerMaster()
        lm.start_new_ledger(master.account_id, close_time=1000)
        open_ledger = lm.current_ledger()
        spec = SpecState(open_ledger)
        tx = SerializedTransaction.build(
            TxType.ttPAYMENT, master.account_id, 1, 10,
            {sfAmount: STAmount.from_drops(1_000_000),
             sfDestination: KeyPair.from_passphrase("ctd-d").account_id},
        )
        tx.sign(master)
        # hand-built record: the engine never produces this shape via
        # payments, so script the write set directly (the compaction
        # in speculate() is mirrored by constructing write_items +
        # net_deletes exactly as it would)
        compact: dict = {}
        ever_set: set = set()
        for k, item in writes_script:
            compact[k] = item
            if item is not None:
                ever_set.add(k)
        write_items = [(k, it) for k, it in compact.items()]
        meta = STObject()
        meta[sfTransactionIndex] = 0
        rec = SpecRecord(
            raw_ter=TER.tesSUCCESS, ter=TER.tesSUCCESS, did_apply=True,
            reads={}, succs=[], write_items=write_items, meta=meta,
            fee=10,
        )
        rec.net_deletes = frozenset(
            k for k, it in compact.items() if it is None and k in ever_set
        )
        spec.records[tx.txid()] = rec

        close_ledger = lm.closed_ledger().open_successor()
        replay = CloseReplay(spec, close_ledger)
        engine = TransactionEngine(close_ledger)
        hit = replay.try_splice(engine, tx, final=True)
        assert hit == (TER.tesSUCCESS, True)
        replay.flush_pending()  # the regression raised KeyError here
        return close_ledger, replay

    def test_bare_delete_of_created_key_nets_to_nothing(self):
        k = h("ctd-key")
        item = SHAMapItem(k, b"ephemeral")
        ledger, _replay = self._splice_record([(k, item), (k, None)])
        assert ledger.state_map.get(k) is None
        # and the tx itself landed in the tx map
        assert len(list(ledger.tx_map.leaves())) == 1

    def test_genuine_missing_delete_still_raises(self):
        k = h("ctd-missing")
        with pytest.raises(KeyError):
            self._splice_record([(k, None)])  # never created: del_item parity
