"""Device-resident tree hashing (TpuHasher.hash_tree) vs the host path.

The whole dirty SHAMap must produce bit-identical node hashes through
the device pipeline (masked leaf kernel + on-device inner-payload
scatter) as through hashlib, on random tree shapes including oversized
leaves and deep replay-style mutations.
"""

from __future__ import annotations

import numpy as np
import pytest

from stellard_tpu.crypto.backend import CpuHasher, TpuHasher
from stellard_tpu.state.shamap import SHAMap, SHAMapItem, TNType
from stellard_tpu.utils.hashes import prefix_hash


def build_map(n_items: int, seed: int, big_every: int = 0) -> SHAMap:
    rng = np.random.default_rng(seed)
    m = SHAMap(TNType.ACCOUNT_STATE)
    for i in range(n_items):
        tag = rng.bytes(32)
        size = int(rng.integers(40, 600))
        if big_every and i % big_every == 0:
            size = 3000  # oversized leaf: beyond the device ladder
        m.set_item(SHAMapItem(tag, rng.bytes(size)))
    return m


class TestTreeHash:
    @pytest.mark.parametrize("n,big", [(1, 0), (17, 0), (200, 23), (500, 0)])
    def test_matches_host_hashing(self, n, big):
        want = build_map(n, seed=n)
        got = build_map(n, seed=n)
        want.hash_batch = CpuHasher()
        got.hash_batch = TpuHasher()
        if big:
            pass  # big leaves exercised via the dedicated case below
        assert want.get_hash() == got.get_hash()

    def test_oversized_leaves_fall_back_to_host(self):
        want = build_map(64, seed=9, big_every=7)
        got = build_map(64, seed=9, big_every=7)
        want.hash_batch = CpuHasher()
        got.hash_batch = TpuHasher()
        assert want.get_hash() == got.get_hash()

    def test_incremental_rehash_after_mutation(self):
        """Replay shape: mutate a hashed tree; only the dirty spine
        rehashes, and it still matches the host oracle."""
        rng = np.random.default_rng(5)
        a = build_map(120, seed=4)
        b = build_map(120, seed=4)
        a.hash_batch = CpuHasher()
        b.hash_batch = TpuHasher()
        assert a.get_hash() == b.get_hash()
        for _ in range(3):
            tag = rng.bytes(32)
            data = rng.bytes(100)
            a.set_item(SHAMapItem(tag, data))
            b.set_item(SHAMapItem(tag, data))
            assert a.get_hash() == b.get_hash()

    def test_flat_batch_path_matches(self):
        rng = np.random.default_rng(6)
        prefixes = [0x4D494E00, 0x534E4400, 0x54584E00] * 10
        payloads = [rng.bytes(int(rng.integers(10, 2500))) for _ in range(30)]
        cpu = CpuHasher().prefix_hash_batch(prefixes, payloads)
        tpu = TpuHasher().prefix_hash_batch(prefixes, payloads)
        assert cpu == tpu
