"""Device-resident tree hashing (TpuHasher.hash_tree) vs the host path.

The whole dirty SHAMap must produce bit-identical node hashes through
the device pipeline (masked leaf kernel + on-device inner-payload
scatter) as through hashlib, on random tree shapes including oversized
leaves and deep replay-style mutations.
"""

from __future__ import annotations

import numpy as np
import pytest

from stellard_tpu.crypto.backend import CpuHasher, TpuHasher
from stellard_tpu.state.shamap import SHAMap, SHAMapItem, TNType
from stellard_tpu.utils.hashes import prefix_hash


def build_map(n_items: int, seed: int, big_every: int = 0) -> SHAMap:
    rng = np.random.default_rng(seed)
    m = SHAMap(TNType.ACCOUNT_STATE)
    for i in range(n_items):
        tag = rng.bytes(32)
        size = int(rng.integers(40, 600))
        if big_every and i % big_every == 0:
            size = 3000  # oversized leaf: beyond the device ladder
        m.set_item(SHAMapItem(tag, rng.bytes(size)))
    return m


class TestTreeHash:
    @pytest.mark.parametrize("n,big", [(1, 0), (17, 0), (200, 23), (500, 0)])
    def test_matches_host_hashing(self, n, big):
        want = build_map(n, seed=n)
        got = build_map(n, seed=n)
        want.hash_batch = CpuHasher()
        got.hash_batch = TpuHasher()
        if big:
            pass  # big leaves exercised via the dedicated case below
        assert want.get_hash() == got.get_hash()

    def test_oversized_leaves_fall_back_to_host(self):
        want = build_map(64, seed=9, big_every=7)
        got = build_map(64, seed=9, big_every=7)
        want.hash_batch = CpuHasher()
        got.hash_batch = TpuHasher()
        assert want.get_hash() == got.get_hash()

    def test_incremental_rehash_after_mutation(self):
        """Replay shape: mutate a hashed tree; only the dirty spine
        rehashes, and it still matches the host oracle."""
        rng = np.random.default_rng(5)
        a = build_map(120, seed=4)
        b = build_map(120, seed=4)
        a.hash_batch = CpuHasher()
        b.hash_batch = TpuHasher()
        assert a.get_hash() == b.get_hash()
        for _ in range(3):
            tag = rng.bytes(32)
            data = rng.bytes(100)
            a.set_item(SHAMapItem(tag, data))
            b.set_item(SHAMapItem(tag, data))
            assert a.get_hash() == b.get_hash()

    def test_flat_batch_path_matches(self):
        rng = np.random.default_rng(6)
        prefixes = [0x4D494E00, 0x534E4400, 0x54584E00] * 10
        payloads = [rng.bytes(int(rng.integers(10, 2500))) for _ in range(30)]
        cpu = CpuHasher().prefix_hash_batch(prefixes, payloads)
        tpu = TpuHasher().prefix_hash_batch(prefixes, payloads)
        assert cpu == tpu


class TestFusedMeshWidths:
    """The fused whole-tree pipeline is ONE sharded program set: roots
    must be byte-identical to the host oracle at every mesh width (the
    8 virtual devices let widths 1/2/4/8 run in-process), and provenance
    must report the width that actually ran."""

    @pytest.mark.parametrize("width", [1, 2, 4, 8])
    def test_root_identity_at_width(self, width):
        want = build_map(300, seed=31)
        want.hash_batch = CpuHasher()
        got = build_map(300, seed=31)
        h = TpuHasher(mesh=str(width))
        got.hash_batch = h
        assert want.get_hash() == got.get_hash()
        d = h.describe()
        assert d["tree_width"] == width
        assert d["tree_kernel"] == f"tree-sha512-sharded@{width}"

    def test_fused_vs_staged_identity(self):
        """[tree] fused=0 (staged per-level hash_packed) and fused=1
        (whole-tree device pipeline) must agree byte-for-byte."""
        host = build_map(250, seed=32)
        host.hash_batch = CpuHasher()
        fused = build_map(250, seed=32)
        fused.hash_batch = TpuHasher()
        staged = build_map(250, seed=32)
        sh = TpuHasher()
        sh.fused_enabled = False  # the node's cfg.tree_fused kill-switch
        staged.hash_batch = sh
        assert host.get_hash() == fused.get_hash() == staged.get_hash()
        # the kill-switch actually switched: no whole-tree pipeline ran
        assert sh.tree_calls == 0
        assert staged.hash_batch.tree_transfers.readbacks == 0


class TestTransferHonesty:
    """The residency pin (ISSUE 16): one fused tree hash performs
    exactly ONE host-blocking device->host transfer, however many
    levels the tree has — a per-level round-trip is a regression this
    counter catches."""

    def test_one_readback_per_tree(self):
        m = build_map(400, seed=41)  # guaranteed multi-level
        h = TpuHasher()
        m.hash_batch = h
        m.get_hash()
        assert h.tree_calls == 1
        assert h.tree_transfers.readbacks == 1
        # multi-level chain: more than one program dispatched, still
        # one readback (this is what "device-resident" means)
        assert h.tree_transfers.uploads > 1

    def test_readbacks_stay_constant_per_close(self):
        """Repeated closes (mutate + rehash) each add exactly one
        readback: the per-close transfer set is CONSTANT."""
        rng = np.random.default_rng(42)
        m = build_map(200, seed=42)
        h = TpuHasher()
        m.hash_batch = h
        m.get_hash()
        for i in range(3):
            before = h.tree_transfers.readbacks
            for _ in range(5):
                m.set_item(SHAMapItem(rng.bytes(32), rng.bytes(120)))
            m.get_hash()
            assert h.tree_transfers.readbacks == before + 1
        assert h.tree_transfers.readbacks == h.tree_calls

    def test_flat_path_meters_separately(self):
        h = TpuHasher()
        rng = np.random.default_rng(43)
        h.prefix_hash_batch([0x4D494E00] * 80,
                            [rng.bytes(64) for _ in range(80)])
        assert h.transfers.readbacks >= 1
        assert h.transfers.get_json()["bytes_moved"] > 0
        assert h.tree_transfers.readbacks == 0  # tree meter untouched

    def test_watched_transfer_json_sees_tree_readbacks(self):
        """The close.device.transfer span reads the WATCHED aggregate:
        it must include the whole-tree pipeline's meter, not just the
        flat hash_packed one."""
        from stellard_tpu.crypto.backend import make_watched_hasher

        h = make_watched_hasher("tpu", routing="device",
                                min_device_nodes=0)
        m = build_map(150, seed=44)
        m.hash_batch = h
        m.get_hash()
        j = h.transfer_json()
        assert j is not None
        assert j["readbacks"] >= 1
        assert j["transfers"] == j["uploads"] + j["readbacks"]
        assert h.get_json()["transfers"] == j

    def test_verifier_meters_transfers(self):
        from stellard_tpu.crypto.backend import TpuVerifier, VerifyRequest
        from stellard_tpu.protocol.keys import KeyPair

        kp = KeyPair.from_passphrase("transfer-honesty")
        msg = b"\x5a" * 32
        reqs = [VerifyRequest(kp.public, msg, kp.sign(msg))
                for _ in range(16)]
        v = TpuVerifier(min_batch=1)
        flags = v.verify_batch(reqs)
        assert flags.all()
        assert v.transfers.uploads >= 1
        assert v.transfers.readbacks >= 1
        assert v.transfers.get_json()["transfers"] == (
            v.transfers.uploads + v.transfers.readbacks
        )
