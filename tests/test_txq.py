"""Admission-control plane tests (node/txq.py + its integrations).

Covers the [txq] subsystem end to end: the adaptive soft cap and
escalation curve, queue admission (replace-by-fee, per-account chains,
account caps, cheapest-first eviction, expiry), close-time promotion in
fee order, byte-identity of the enabled=0 kill-switch at capacity, the
bounded/expiring held pile, queue-aware speculation (promoted txs
splice at their close), the LoadFeeTrack queue-fee feedback, the
LocalTxs resubmit regression, and the RPC surfaces (fee,
account_info queue block, submit terQUEUED, get_counts/server_state).
"""

from __future__ import annotations

import pytest

from stellard_tpu.node import ledgermaster as lm_mod
from stellard_tpu.node.config import Config
from stellard_tpu.node.localtxs import LocalTxs
from stellard_tpu.node.loadmgr import NORMAL_FEE, LoadFeeTrack
from stellard_tpu.node.node import Node
from stellard_tpu.node.txq import NORMAL_LEVEL, FeeMetrics, fee_level
from stellard_tpu.protocol.formats import TxType
from stellard_tpu.protocol.keys import KeyPair
from stellard_tpu.protocol.sfields import sfAmount, sfBalance, sfDestination
from stellard_tpu.protocol.stamount import STAmount
from stellard_tpu.protocol.sttx import SerializedTransaction
from stellard_tpu.protocol.ter import TER
from stellard_tpu.rpc.handlers import Context, Role, dispatch

XRP = 1_000_000


def payment(kp, seq, dest, drops, fee=10):
    tx = SerializedTransaction.build(
        TxType.ttPAYMENT, kp.account_id, seq, fee,
        {sfAmount: STAmount.from_drops(drops), sfDestination: dest},
    )
    tx.sign(kp)
    return tx


def make_node(**cfg_kwargs):
    node = Node(Config(**cfg_kwargs)).setup()
    # deterministic close times: one resolution step per close
    closes = [0]
    real_close = node.close_ledger

    def close():
        closes[0] += 1
        return real_close()

    node.ops.network_time = lambda: 900_000_000 + closes[0] * 30
    node.close_ledger = close
    return node


def fund(node, kp, drops=2_000 * XRP):
    # the fee beats any escalation these small test caps can produce, so
    # funding always enters the open ledger directly
    seq = node._fund_seq = getattr(node, "_fund_seq", 0) + 1
    ter, ok = node.submit(
        payment(node.master_keys, seq, kp.account_id, drops, fee=10_000_000)
    )
    assert ok, ter


class TestFeeMetrics:
    def test_required_level_curve(self):
        m = FeeMetrics(min_cap=8, max_cap=8)
        assert m.txns_expected == 8
        assert m.required_level(0) == NORMAL_LEVEL
        assert m.required_level(7) == NORMAL_LEVEL
        at_cap = m.required_level(8)
        assert at_cap > NORMAL_LEVEL
        # quadratic growth above the cap
        assert m.required_level(16) > 2 * at_cap

    def test_cap_adapts_to_measured_capacity(self):
        m = FeeMetrics(min_cap=8, max_cap=1000, target_close_ms=100.0)
        # 1 ms/tx measured -> 100 txs fit the 100 ms budget
        for _ in range(8):
            m.note_close(50, 50.0)
        assert 90 <= m.txns_expected <= 110
        # closes slow down 10x -> the cap shrinks toward 10
        for _ in range(16):
            m.note_close(50, 500.0)
        assert m.txns_expected <= 16
        # empty closes carry no signal
        before = m.txns_expected
        m.note_close(0, 1000.0)
        assert m.txns_expected == before

    def test_clamps(self):
        m = FeeMetrics(min_cap=8, max_cap=16, target_close_ms=1000.0)
        m.note_close(100, 0.001)  # absurdly fast: clamp at max
        assert m.txns_expected == 16
        for _ in range(16):
            m.note_close(10, 10_000.0)  # absurdly slow: clamp at min
        assert m.txns_expected == 8


class TestAdmission:
    """Queue admission against a pinned cap (min_cap == max_cap)."""

    @pytest.fixture
    def node(self):
        n = make_node(txq_min_cap=4, txq_max_cap=4,
                      txq_ledgers_in_queue=2, txq_account_cap=3)
        yield n
        n.stop()

    @pytest.fixture
    def funded(self, node):
        senders = [KeyPair.from_passphrase(f"adm-{i}") for i in range(8)]
        for s in senders:
            fund(node, s)
        node.close_ledger()
        return senders

    def test_direct_under_cap_then_queue_above(self, node, funded):
        senders = funded
        results = []
        for i, s in enumerate(senders):
            ter, ok = node.submit(
                payment(s, 1, node.master_keys.account_id, XRP)
            )
            results.append((ter, ok))
        # first 4 fill the open ledger, the rest queue
        assert [r for r, ok in results[:4]] == [TER.tesSUCCESS] * 4
        assert all(r == TER.terQUEUED for r, _ in results[4:])
        assert len(node.txq) == 4
        # the escalated fee buys entry even above the cap
        rich = senders[0]
        fee = int(dispatch(
            Context(node=node, params={}, role=Role.ADMIN), "fee"
        )["drops"]["open_ledger_fee"])
        assert fee > 10
        ter, ok = node.submit(
            payment(rich, 2, node.master_keys.account_id, XRP, fee=fee)
        )
        assert ter == TER.tesSUCCESS and ok

    def test_replace_by_fee(self, node, funded):
        senders = funded
        for s in senders[:4]:
            node.submit(payment(s, 1, node.master_keys.account_id, XRP))
        q = senders[4]
        ter, _ = node.submit(payment(q, 1, node.master_keys.account_id, XRP, fee=100))
        assert ter == TER.terQUEUED
        # an insufficient bump (<25%) is rejected resubmittably
        ter, _ = node.submit(payment(q, 1, node.master_keys.account_id, XRP, fee=110))
        assert ter == TER.telINSUF_FEE_P
        # >= 25% bump replaces the queued entry
        ter, _ = node.submit(payment(q, 1, node.master_keys.account_id, XRP, fee=125))
        assert ter == TER.terQUEUED
        assert node.txq.stats["replaced"] == 1
        qd = node.txq.account_json(q.account_id)
        assert qd["txn_count"] == 1
        assert int(qd["transactions"][0]["fee_level"]) == fee_level(125, 10)

    def test_account_chain_cap(self, node, funded):
        senders = funded
        for s in senders[:4]:
            node.submit(payment(s, 1, node.master_keys.account_id, XRP))
        q = senders[5]
        for seq in (1, 2, 3):
            ter, _ = node.submit(payment(q, seq, node.master_keys.account_id, XRP))
            assert ter == TER.terQUEUED
        ter, _ = node.submit(payment(q, 4, node.master_keys.account_id, XRP))
        assert ter == TER.telINSUF_FEE_P  # account_cap=3

    def test_eviction_cheapest_first(self, node, funded):
        senders = funded
        for s in senders[:4]:
            node.submit(payment(s, 1, node.master_keys.account_id, XRP))
        # fill the queue bound (max_size = 4*2 = 8) with cheap entries
        cheap = senders[4:8]
        for s in cheap:
            for seq in (1, 2):
                ter, _ = node.submit(
                    payment(s, seq, node.master_keys.account_id, XRP, fee=10)
                )
                assert ter == TER.terQUEUED
        assert len(node.txq) == node.txq.max_size == 8
        # an equal-fee newcomer is shed (FIFO within level: no eviction)
        ter, _ = node.submit(
            payment(senders[0], 2, node.master_keys.account_id, XRP, fee=10)
        )
        assert ter == TER.telINSUF_FEE_P
        # a better-paying newcomer evicts the cheapest
        ter, _ = node.submit(
            payment(senders[0], 2, node.master_keys.account_id, XRP, fee=40)
        )
        assert ter == TER.terQUEUED
        assert node.txq.stats["evicted"] == 1
        assert len(node.txq) == 8

    def test_eviction_never_gaps_own_chain(self, node, funded):
        """A full queue must shed a newcomer rather than evict the
        newcomer's OWN chain tail to make room for its later sequence —
        that would manufacture an unpromotable mid-chain gap."""
        senders = funded
        for s in senders[:4]:
            node.submit(payment(s, 1, node.master_keys.account_id, XRP))
        q = senders[4]
        # fill the whole bound (8) from ONE account (account_cap is 3
        # here, so use a node-level override)
        node.txq.account_cap = 16
        for seq in range(1, 9):
            ter, _ = node.submit(
                payment(q, seq, node.master_keys.account_id, XRP, fee=10)
            )
            assert ter == TER.terQUEUED
        # a much better-paying seq 9 from the SAME account must be shed,
        # not evict seq 8 out from under itself
        ter, _ = node.submit(
            payment(q, 9, node.master_keys.account_id, XRP, fee=500)
        )
        assert ter == TER.telINSUF_FEE_P
        assert sorted(node.txq._accounts[q.account_id]) == list(range(1, 9))
        assert node.txq.stats["evicted"] == 0

    def test_drop_hook_fires_on_evict_and_expiry(self, node, funded):
        senders = funded
        dropped = []
        node.txq.on_drop = dropped.append
        node.txq.retention_ledgers = 1  # horizons stamp at queue time
        for s in senders[:4]:
            node.submit(payment(s, 1, node.master_keys.account_id, XRP))
        # fill the bound with cheap entries from several accounts, then
        # evict one with a better-paying newcomer
        for s in senders[4:8]:
            for seq in (1, 2):
                node.submit(payment(s, seq, node.master_keys.account_id,
                                    XRP, fee=10))
        tx_evictor = payment(senders[0], 2, node.master_keys.account_id,
                             XRP, fee=40)
        assert node.submit(tx_evictor)[0] == TER.terQUEUED
        assert len(dropped) == 1  # the evicted chain tail
        # expiry notifies too (anything promotion doesn't drain first)
        for _ in range(3):
            node.close_ledger()
        assert len(dropped) >= 2

    def test_queue_rejects_hopeless_txs(self, node, funded):
        senders = funded
        for s in senders[:4]:
            node.submit(payment(s, 1, node.master_keys.account_id, XRP))
        ghost = KeyPair.from_passphrase("txq-ghost")
        ter, _ = node.submit(payment(ghost, 1, node.master_keys.account_id, XRP))
        assert ter == TER.terNO_ACCOUNT
        # past sequence can never apply
        ter, _ = node.submit(
            payment(node.master_keys, 1, senders[0].account_id, XRP)
        )
        assert ter == TER.tefPAST_SEQ

    def test_expiry_by_ledger_seq(self, node, funded):
        senders = funded
        for s in senders[:4]:
            node.submit(payment(s, 1, node.master_keys.account_id, XRP))
        gap = senders[6]
        node.txq.retention_ledgers = 2
        ter, _ = node.submit(payment(gap, 5, node.master_keys.account_id, XRP))
        assert ter == TER.terQUEUED  # future seq: can never promote
        for _ in range(4):
            node.close_ledger()
        assert node.txq.stats["expired"] >= 1
        assert node.txq.account_json(gap.account_id)["txn_count"] == 0

    def test_malformed_fee_future_seq_never_held_and_dropped(self, node,
                                                             funded):
        """A non-native-fee tx takes the malformed-fee bypass in
        admit(). NetworkOPs skips the legacy hold pile when the queue is
        on, so terPRE_SEQ escaping that bypass would report HELD while
        silently dropping the tx. Today the engine's passes_local_checks
        gate makes that unreachable (temINVALID before the sequence
        check); this pins the contract either way — the outcome must be
        a hard reject or terQUEUED, never a HELD status with the tx in
        no retry structure."""
        s = funded[0]
        tx = SerializedTransaction.build(
            TxType.ttPAYMENT, s.account_id, 5, 10,
            {sfAmount: STAmount.from_drops(XRP),
             sfDestination: node.master_keys.account_id},
        )
        from stellard_tpu.node.networkops import TxStatus
        from stellard_tpu.protocol.sfields import sfFee
        tx.obj[sfFee] = STAmount.from_iou(b"USD\0" * 5, s.account_id, 10, 0)
        tx.sign(s)
        ter, did_apply = node.submit(tx)
        assert ter == TER.temINVALID and not did_apply
        assert node.tx_status(tx.txid()) == TxStatus.INVALID
        assert node.txq.account_json(s.account_id)["txn_count"] == 0

    def test_chain_cumulative_spend_bounded_by_balance(self, node):
        """The WHOLE chain's queued fees must be payable, not just each
        tx's own: a chain whose cumulative fees exceed the balance would
        squat in the queue as unpromotable terINSUF_FEE_B retries until
        expiry. Future-seq txs queue regardless of fee level (the
        terPRE_SEQ fold), so high fees are the easiest squat vector."""
        poor = KeyPair.from_passphrase("cumul-poor")
        fund(node, poor, drops=300 * XRP)
        node.close_ledger()
        fee = 120 * XRP  # each affordable alone; three exceed 300
        for seq, want in ((5, TER.terQUEUED), (6, TER.terQUEUED),
                          (7, TER.terINSUF_FEE_B)):
            ter, _ = node.submit(payment(
                poor, seq, node.master_keys.account_id, 1, fee=fee
            ))
            assert ter == want, (seq, ter)
        assert node.txq.account_json(poor.account_id)["txn_count"] == 2


class TestPromotion:
    def test_fee_order_drain(self):
        """A drained queue validates strictly in fee-level order."""
        node = make_node(txq_min_cap=2, txq_max_cap=2)
        node.txq.spec_dispatch = None  # inline speculation: deterministic
        senders = [KeyPair.from_passphrase(f"promo-{i}") for i in range(6)]
        for s in senders:
            fund(node, s)
        node.close_ledger()
        fees = [10, 11, 12, 13, 14, 15]  # submit cheapest first
        txs = [
            payment(s, 1, node.master_keys.account_id, XRP, fee=f)
            for s, f in zip(senders, fees)
        ]
        for tx in txs:
            node.submit(tx)
        # open holds the 2 direct ones; 4 queued
        assert len(node.txq) == 4
        landed = {}
        for _ in range(4):
            closed, results = node.ops.accept_ledger()
            for txid in results:
                landed[txid] = closed.seq
        by_fee = {tx.fee.mantissa: landed.get(tx.txid()) for tx in txs}
        assert all(v is not None for v in by_fee.values()), by_fee
        # the queued ones (12..15) drain highest-fee-first
        assert by_fee[15] <= by_fee[14] <= by_fee[13] <= by_fee[12]
        assert node.txq.stats["promoted"] == 4
        node.stop()

    def test_account_chain_promotes_in_sequence(self):
        node = make_node(txq_min_cap=2, txq_max_cap=2)
        node.txq.spec_dispatch = None
        a = KeyPair.from_passphrase("chain-a")
        b = KeyPair.from_passphrase("chain-b")
        for s in (a, b):
            fund(node, s)
        node.close_ledger()
        # fill the open window
        node.submit(payment(b, 1, node.master_keys.account_id, XRP, fee=500))
        node.submit(payment(b, 2, node.master_keys.account_id, XRP, fee=500))
        # queue a 3-tx chain where the LATER seqs pay more: promotion
        # must still apply seq 1 first (chains stay ordered)
        for seq, fee in ((1, 10), (2, 40), (3, 80)):
            ter, _ = node.submit(
                payment(a, seq, node.master_keys.account_id, XRP, fee=fee)
            )
            assert ter == TER.terQUEUED
        for _ in range(3):
            node.close_ledger()
        led = node.ledger_master.closed_ledger()
        root = led.account_root(a.account_id)
        from stellard_tpu.protocol.sfields import sfSequence

        assert root[sfSequence] == 4  # all three applied, in order
        assert node.txq.stats["promoted"] == 3
        node.stop()

    def test_deferred_promotion_reaches_committed_status(self):
        """A queued tx promoted on the deferred job must end COMMITTED
        once its ledger closes — the HELD->INCLUDED transition from the
        relay drain lands BEFORE the publish's COMMITTED promotion."""
        from stellard_tpu.node.networkops import TxStatus

        node = make_node(txq_min_cap=2, txq_max_cap=2)
        senders = [KeyPair.from_passphrase(f"st-{i}") for i in range(3)]
        for s in senders:
            fund(node, s)
        node.close_ledger()
        for s in senders[:2]:
            node.submit(payment(s, 1, node.master_keys.account_id, XRP))
        queued_tx = payment(senders[2], 1, node.master_keys.account_id, XRP)
        ter, _ = node.submit(queued_tx)
        assert ter == TER.terQUEUED
        assert node.tx_status(queued_tx.txid()) == TxStatus.HELD
        node.close_ledger()  # promotes (deferred; close_ledger quiesces)
        node.close_ledger()  # commits + publishes
        assert node.tx_status(queued_tx.txid()) == TxStatus.COMMITTED
        node.stop()

    def test_queue_aware_speculation_splices(self):
        """Promoted txs splice at their close via the deferred
        speculation (no transactor re-execution) — the get_counts.txq
        honesty counter for the queue-aware-speculation claim."""
        node = make_node(txq_min_cap=4, txq_max_cap=4)
        node.txq.spec_dispatch = None  # run spec inline (deterministic)
        senders = [KeyPair.from_passphrase(f"spec-{i}") for i in range(12)]
        for s in senders:
            fund(node, s)
        node.close_ledger()
        # disjoint destinations: payments create per-sender accounts, so
        # canonical-order scrambling cannot invalidate overlay reads
        # (a shared hot destination falls back by design)
        for i, s in enumerate(senders):
            dest = KeyPair.from_passphrase(f"spec-dest-{i}").account_id
            node.submit(payment(s, 1, dest, 250 * XRP))
        for _ in range(4):
            node.close_ledger()
        j = node.txq.get_json()
        assert j["promoted"] == 8  # 4 direct + 8 promoted
        assert j["promote_spliced"] == 8
        assert j["promote_fallback"] == 0
        node.stop()

    def test_promotion_budget_respects_open_occupancy(self):
        """_promote fills UP TO the soft cap: txs already in the open
        window (consensus leftovers, an earlier promotion pass) count
        against the budget, so a second pass cannot stack a full budget
        on top and close an oversized ledger."""
        node = make_node(txq_min_cap=2, txq_max_cap=2, txq_account_cap=4)
        node.txq.spec_dispatch = None
        senders = [KeyPair.from_passphrase(f"bud-{i}") for i in range(6)]
        for s in senders:
            fund(node, s)
        node.close_ledger()
        for s in senders:
            node.submit(payment(s, 1, node.master_keys.account_id, XRP))
        assert len(node.txq) == 4  # 2 direct, 4 queued
        node.close_ledger()  # inline promotion fills the window to 2
        lm = node.ledger_master
        assert node.txq.open_size(lm.current_ledger()) == 2
        # a second (stacked/stale) pass finds zero budget: the window is
        # already at the cap, so nothing more promotes into it
        with lm._lock:
            again = node.txq._promote(lm)
        assert again == 0
        assert node.txq.open_size(lm.current_ledger()) == 2
        assert len(node.txq) == 2
        node.stop()

    def test_stale_deferred_job_skips_moved_window(self):
        """A deferred promotion job that runs after its target window
        already closed must SKIP (the newer close's job owns the new
        window) — a backed-up job queue must not promote twice into one
        window."""
        node = make_node(txq_min_cap=2, txq_max_cap=2)
        jobs = []
        node.txq.spec_dispatch = lambda thunk: (jobs.append(thunk), True)[1]
        senders = [KeyPair.from_passphrase(f"stale-{i}") for i in range(5)]
        for s in senders:
            fund(node, s)
        node.ops.accept_ledger()
        jobs.clear()  # replenish jobs for the pre-flood closes
        for s in senders:
            node.submit(payment(s, 1, node.master_keys.account_id, XRP))
        node.ops.accept_ledger()   # job A targets window N
        node.ops.accept_ledger()   # job B targets window N+1; A is stale
        assert len(jobs) == 2
        job_a, job_b = jobs
        before = node.txq.stats["promoted"]
        job_a()  # stale: its window moved on -> must be a no-op
        assert node.txq.stats["promoted"] == before
        assert node.txq.open_size(node.ledger_master.current_ledger()) == 0
        job_b()  # current: promotes into the live window
        assert node.txq.stats["promoted"] == before + 2
        node.stop()


class TestKillSwitchIdentity:
    def _drive(self, enabled):
        node = make_node(txq_enabled=enabled, txq_min_cap=64, txq_max_cap=64)
        if enabled:
            node.txq.spec_dispatch = None
        senders = [KeyPair.from_passphrase(f"ident-{i}") for i in range(4)]
        for s in senders:
            fund(node, s)
        hashes = [node.close_ledger()[0].hash()]
        # at-capacity workload with a sequence gap thrown in: the gap is
        # held (enabled=0) or queued (enabled=1) and lands next close
        results_log = []
        for rnd in range(3):
            for i, s in enumerate(senders):
                node.submit(payment(s, rnd + 1, node.master_keys.account_id,
                                    XRP, fee=10 + i))
            if rnd == 0:
                # future seq for sender 0 — a terPRE_SEQ hold
                node.submit(payment(senders[0], 3, node.master_keys.account_id,
                                    2 * XRP))
            closed, results = node.ops.accept_ledger()
            hashes.append(closed.hash())
            results_log.append(sorted(
                (txid.hex(), int(ter)) for txid, ter in results.items()
            ))
        closed, results = node.ops.accept_ledger()  # gap tx lands
        hashes.append(closed.hash())
        results_log.append(sorted(
            (txid.hex(), int(ter)) for txid, ter in results.items()
        ))
        node.stop()
        return hashes, results_log

    def test_enabled_0_vs_1_byte_identical_at_capacity(self):
        h0, r0 = self._drive(enabled=False)
        h1, r1 = self._drive(enabled=True)
        assert h0 == h1  # every close byte-identical
        assert r0 == r1


class TestHeldPileBounds:
    """Satellite: the legacy held dict is capped and expires by seq."""

    def test_cap_evicts_oldest(self, monkeypatch):
        monkeypatch.setattr(lm_mod, "HELD_CAP", 4)
        node = make_node(txq_enabled=False)
        a = KeyPair.from_passphrase("held-a")
        fund(node, a)
        node.close_ledger()
        for seq in range(10, 17):  # 7 gapped holds, cap 4
            ter, _ = node.submit(payment(a, seq, node.master_keys.account_id, XRP))
            assert ter == TER.terPRE_SEQ
        assert len(node.ledger_master.held) == 4
        assert node.ledger_master.held_stats["evicted"] == 3
        node.stop()

    def test_expiry_by_ledger_seq(self, monkeypatch):
        monkeypatch.setattr(lm_mod, "HELD_EXPIRE_LEDGERS", 2)
        node = make_node(txq_enabled=False)
        a = KeyPair.from_passphrase("held-b")
        fund(node, a)
        node.close_ledger()
        ter, _ = node.submit(payment(a, 9, node.master_keys.account_id, XRP))
        assert ter == TER.terPRE_SEQ
        for _ in range(4):
            node.close_ledger()
        assert len(node.ledger_master.held) == 0
        assert node.ledger_master.held_stats["expired"] >= 1
        node.stop()

    def test_rehold_keeps_original_horizon(self, monkeypatch):
        monkeypatch.setattr(lm_mod, "HELD_EXPIRE_LEDGERS", 3)
        node = make_node(txq_enabled=False)
        a = KeyPair.from_passphrase("held-c")
        fund(node, a)
        node.close_ledger()
        node.submit(payment(a, 9, node.master_keys.account_id, XRP))
        key = next(iter(node.ledger_master.held))
        first_expire = node.ledger_master.held[key][1]
        node.close_ledger()  # re-held with the SAME horizon
        assert node.ledger_master.held[key][1] == first_expire
        node.stop()

    def test_rejected_held_absorption_fires_drop_hook(self):
        """A held tx the queue REFUSES at absorption (queue full of
        better payers) is dropped — the drop hook must fire so LocalTxs
        stops the cross-round re-apply; silent discard would let the tx
        bypass admission forever."""
        node = make_node(txq_min_cap=2, txq_max_cap=2,
                         txq_ledgers_in_queue=1)
        node.txq.spec_dispatch = None
        senders = [KeyPair.from_passphrase(f"habs-{i}") for i in range(5)]
        for s in senders:
            fund(node, s)
        node.close_ledger()
        for s in senders[:2]:
            node.submit(payment(s, 1, node.master_keys.account_id, XRP,
                                fee=500))
        for s in senders[2:4]:  # fill the queue (max_size = 2)
            ter, _ = node.submit(payment(
                s, 1, node.master_keys.account_id, XRP, fee=100
            ))
            assert ter == TER.terQUEUED
        dropped = []
        node.txq.on_drop = dropped.append
        held = payment(senders[4], 3, node.master_keys.account_id, XRP)
        node.ledger_master.add_held_transaction(held)
        node.close_ledger()  # absorption finds the queue full -> drop
        assert held.txid() in dropped
        assert node.txq.account_json(senders[4].account_id)["txn_count"] == 0
        node.stop()


class TestLocalTxsResubmit:
    """Satellite: a queued-then-evicted local tx stays resubmittable."""

    def test_push_back_revives_failed_entry(self):
        lt = LocalTxs()
        kp = KeyPair.from_passphrase("lt")
        tx = payment(kp, 1, KeyPair.from_passphrase("lt2").account_id, XRP)
        lt.push_back(5, tx)
        lt._txns[tx.txid()].failed = True  # apply_to_open marked it
        # resubmission (same txid) must revive tracking, not be
        # shadowed by the stale failed mark
        lt.push_back(9, tx)
        assert not lt._txns[tx.txid()].failed
        assert lt._txns[tx.txid()].submit_seq == 9

    def test_remove_unshadows(self):
        lt = LocalTxs()
        kp = KeyPair.from_passphrase("lt3")
        tx = payment(kp, 1, KeyPair.from_passphrase("lt4").account_id, XRP)
        lt.push_back(5, tx)
        assert tx.txid() in lt
        assert lt.remove(tx.txid())
        assert tx.txid() not in lt
        lt.push_back(6, tx)  # fresh horizon after eviction
        assert lt._txns[tx.txid()].submit_seq == 6


class TestQueueFeeFeedback:
    def test_queue_fee_folds_into_load_factor_not_floor(self):
        ft = LoadFeeTrack()
        assert ft.load_factor == NORMAL_FEE
        ft.set_queue_fee(4 * NORMAL_FEE)
        assert ft.load_factor == 4 * NORMAL_FEE
        assert ft.queue_fee == 4 * NORMAL_FEE
        # the NETWORK floor excludes local admission escalation
        assert ft.network_floor == NORMAL_FEE
        assert ft.get_json()["queue_fee"] == 4 * NORMAL_FEE
        ft.set_queue_fee(0)  # clamped at normal
        assert ft.load_factor == NORMAL_FEE

    def test_close_feeds_escalation_into_track(self):
        node = make_node(txq_min_cap=2, txq_max_cap=2)
        node.txq.spec_dispatch = None  # inline replenish: deterministic
        senders = [KeyPair.from_passphrase(f"fb-{i}") for i in range(6)]
        for s in senders:
            fund(node, s)
        node.close_ledger()
        for s in senders:
            node.submit(payment(s, 1, node.master_keys.account_id, XRP))
        node.close_ledger()
        # promotion refilled the open ledger to the cap: the escalated
        # entry price is visible as the track's queue component
        assert node.fee_track.queue_fee > NORMAL_FEE
        assert node.fee_track.load_factor >= node.fee_track.queue_fee
        # drain fully: feedback decays back to normal
        for _ in range(4):
            node.close_ledger()
        assert node.fee_track.queue_fee == NORMAL_FEE
        node.stop()

    def test_queue_fee_never_stamps_the_open_ledger(self):
        """The submit path stamps the ledger's load_factor with the
        NETWORK floor only: folding the queue escalation in would make
        payFee double-price admission — a base-fee tx submitted while
        the open window has room would shed telINSUF_FEE_P instead of
        applying (and a promoted cheap tx would starve the same way)."""
        node = make_node(txq_min_cap=4, txq_max_cap=4)
        node.txq.spec_dispatch = None
        a = KeyPair.from_passphrase("stamp-a")
        fund(node, a)
        node.close_ledger()
        # simulate standing queue pressure from an earlier close
        node.fee_track.set_queue_fee(500 * NORMAL_FEE)
        ter, ok = node.submit(payment(a, 1, node.master_keys.account_id, XRP))
        assert (ter, ok) == (TER.tesSUCCESS, True)  # room -> applies
        assert node.ledger_master.current_ledger().load_factor == NORMAL_FEE
        # genuine NETWORK load still gates payFee through the stamp
        for _ in range(8):
            node.fee_track.raise_local_fee()
        ter, ok = node.submit(payment(a, 2, node.master_keys.account_id, XRP))
        assert ter == TER.telINSUF_FEE_P and not ok
        assert (node.ledger_master.current_ledger().load_factor
                == node.fee_track.network_floor > NORMAL_FEE)
        node.stop()


class TestRpcSurfaces:
    @pytest.fixture
    def node(self):
        n = make_node(txq_min_cap=2, txq_max_cap=2)
        n.txq.spec_dispatch = None
        yield n
        n.stop()

    def _ctx(self, node, params=None, role=Role.ADMIN):
        return Context(node=node, params=params or {}, role=role)

    def test_fee_method(self, node):
        out = dispatch(self._ctx(node), "fee")
        assert out["levels"]["reference_level"] == "256"
        assert out["expected_ledger_size"] == "2"
        assert int(out["drops"]["open_ledger_fee"]) >= 10

    def test_submit_returns_queued_with_open_fee(self, node):
        senders = [KeyPair.from_passphrase(f"rpc-{i}") for i in range(3)]
        for s in senders:
            fund(node, s)
        node.close_ledger()
        for s in senders[:2]:
            node.submit(payment(s, 1, node.master_keys.account_id, XRP))
        tx = payment(senders[2], 1, node.master_keys.account_id, XRP)
        out = dispatch(
            self._ctx(node, {"tx_blob": tx.serialize().hex()}, Role.GUEST),
            "submit",
        )
        assert out["engine_result"] == "terQUEUED"
        assert out["queued"] is True
        assert int(out["open_ledger_fee"]) > 10

    def test_account_info_queue_block(self, node):
        senders = [KeyPair.from_passphrase(f"ai-{i}") for i in range(3)]
        for s in senders:
            fund(node, s)
        node.close_ledger()
        for s in senders[:2]:
            node.submit(payment(s, 1, node.master_keys.account_id, XRP))
        q = senders[2]
        node.submit(payment(q, 1, node.master_keys.account_id, XRP))
        from stellard_tpu.protocol.keys import encode_account_id

        out = dispatch(
            self._ctx(node, {"account": encode_account_id(q.account_id),
                             "queue": True}),
            "account_info",
        )
        assert out["queue_data"]["txn_count"] == 1
        assert out["queue_data"]["lowest_sequence"] == 1

    def test_counts_and_state_blocks(self, node):
        counts = dispatch(self._ctx(node), "get_counts")
        assert "txq" in counts and counts["txq"]["enabled"] is True
        assert "held" in counts
        state = dispatch(self._ctx(node), "server_state")["state"]
        assert state["txq"]["size"] == 0
        assert "txns_expected" in state["txq"]["metrics"]


class TestOverloadBounded:
    def test_4x_flood_keeps_closes_at_cap(self):
        """The acceptance shape in miniature: a flood 4x the cap never
        grows a closed ledger past the cap, the queue stays bounded,
        and the held pile stays empty."""
        node = make_node(txq_min_cap=8, txq_max_cap=8,
                         txq_ledgers_in_queue=2, txq_account_cap=10)
        node.txq.spec_dispatch = None
        senders = [KeyPair.from_passphrase(f"ov-{i}") for i in range(8)]
        for s in senders:
            fund(node, s)
        node.close_ledger()
        dests = [KeyPair.from_passphrase(f"ov-dest-{i}").account_id
                 for i in range(8)]
        sizes = []
        for rnd in range(4):
            for seq in range(rnd * 4 + 1, rnd * 4 + 5):
                for i, s in enumerate(senders):  # 32/round at cap 8;
                    # later rounds pay more so the bound evicts, not
                    # just sheds; disjoint dests keep splices clean
                    node.submit(payment(s, seq, dests[i], 250 * XRP,
                                        fee=10 + 5 * rnd + seq))
            closed, _ = node.ops.accept_ledger()
            sizes.append(len(list(closed.tx_entries())))
            assert len(node.txq) <= node.txq.max_size
            assert len(node.ledger_master.held) == 0
        assert max(sizes) <= 8
        j = node.txq.get_json()
        assert j["evicted"] > 0  # the bound actually bit
        assert j["promote_spliced"] > 0
        node.stop()
