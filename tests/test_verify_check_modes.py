"""Equivalence of the two final-check formulations of the verify kernel
(STELLARD_VERIFY_CHECK=bytes|point) against the Python oracle.

`bytes` is the reference's exact verify shape (ref10 crypto_sign_open:
encode([S]B + [h](-A)) and byte-compare against R). `point` replaces
the inversion chain with a projective equality against the decompressed
R plus an explicit canonical-y_r check. Consensus splits on ANY verdict
divergence, so the corpus leans adversarial: non-canonical R encodings,
x=0/sign=1 R, off-curve R, the classic small-order identity forgery
(which ref10 semantics ACCEPT — both modes must too), corrupted
R/S/key/message bytes, and non-canonical S.

The env knob is read at kernel import, so each mode runs in a
subprocess.
"""

import os
import subprocess
import sys

import pytest

# wall-clock-heavy (each case compiles + runs the full kernel in a
# subprocess, ~3 min apiece on the CI box): excluded from the tier-1
# `-m 'not slow'` gate; plain `pytest tests/` still runs the corpus
pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_CASE_RUNNER = r'''
import os, sys
import numpy as np
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, %(repo)r)
from stellard_tpu.ops import ed25519_ref as ref
from stellard_tpu.ops.ed25519_jax import P, prepare_batch, verify_kernel
from stellard_tpu.protocol.keys import KeyPair

rng = np.random.default_rng(5)
keys = [
    KeyPair.from_seed(bytes(rng.integers(0, 256, 32, dtype=np.uint8)))
    for _ in range(4)
]
pubs, msgs, sigs = [], [], []
for i in range(24):
    k = keys[i %% 4]
    m = bytes(rng.integers(0, 256, 32, dtype=np.uint8))
    s = bytearray(k.sign(m))
    if i %% 5 == 1:
        s[rng.integers(0, 64)] ^= 1 << rng.integers(0, 8)
    pubs.append(k.public)
    msgs.append(m)
    sigs.append(bytes(s))

ident = (1).to_bytes(32, "little")  # canonical identity-point encoding
zero_s = bytes(32)
m = b"\x42" * 32
# non-canonical R encoding of the identity (y = 1 + p)
pubs.append(ident); msgs.append(m)
sigs.append((1 + P).to_bytes(32, "little") + zero_s)
# x=0 with sign=1: invalid encoding
x0s1 = bytearray(ident); x0s1[31] |= 0x80
pubs.append(ident); msgs.append(m); sigs.append(bytes(x0s1) + zero_s)
# canonical small-order forgery A=R=identity, S=0 (ref10 ACCEPTS this)
forgery_idx = len(pubs)
pubs.append(ident); msgs.append(m); sigs.append(ident + zero_s)
# off-curve R
pubs.append(keys[0].public); msgs.append(m)
sigs.append(b"\x17" * 32 + zero_s)
# non-canonical S (l + small) on an otherwise-valid signature
k = keys[1]; mm = b"\x55" * 32
good = k.sign(mm)
from stellard_tpu.ops.ed25519_ref import L as ED_L
s_nc = int.from_bytes(good[32:], "little") + ED_L
if s_nc < (1 << 256):
    pubs.append(k.public); msgs.append(mm)
    sigs.append(good[:32] + s_nc.to_bytes(32, "little"))

want = np.array([ref.verify(p, mm, s) for p, mm, s in zip(pubs, msgs, sigs)])
got = np.asarray(verify_kernel(**prepare_batch(pubs, msgs, sigs)))
assert got.shape == want.shape
assert (got == want).all(), (
    os.environ.get("STELLARD_VERIFY_CHECK", "bytes"),
    np.nonzero(got != want)[0].tolist(),
)
assert bool(want[forgery_idx]) is True  # forgery IS accepted (ref10)
print("OK", os.environ.get("STELLARD_VERIFY_CHECK", "bytes"), len(pubs))
'''


def _run(mode: str) -> str:
    env = dict(os.environ)
    env["STELLARD_VERIFY_CHECK"] = mode
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run(
        [sys.executable, "-u", "-c", _CASE_RUNNER % {"repo": REPO}],
        env=env,
        capture_output=True,
        text=True,
        timeout=900,
    )
    assert r.returncode == 0, (mode, r.stdout[-2000:], r.stderr[-2000:])
    return r.stdout


def test_bytes_mode_matches_oracle():
    assert "OK bytes" in _run("bytes")


def test_point_mode_matches_oracle():
    assert "OK point" in _run("point")


_MESH_RUNNER = r'''
import os, sys
import numpy as np
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, %(repo)r)
from stellard_tpu.crypto.backend import TpuVerifier, VerifyRequest
from stellard_tpu.ops import ed25519_ref as ref
from stellard_tpu.protocol.keys import KeyPair

assert len(jax.devices()) == 8
rng = np.random.default_rng(9)
keys = [KeyPair.from_seed(rng.bytes(32)) for _ in range(8)]
reqs, want = [], []
for i in range(300):
    k = keys[i %% 8]
    m = rng.bytes(32)
    s = bytearray(k.sign(m))
    if i in (0, 7, 150, 299):
        s[rng.integers(0, 64)] ^= 1 << int(rng.integers(0, 8))
    reqs.append(VerifyRequest(k.public, m, bytes(s)))
    want.append(ref.verify(k.public, m, bytes(s)))
v = TpuVerifier(min_batch=64)
got = v.verify_batch(reqs)
assert v.n_devices == 8
assert np.array_equal(got, np.array(want)), np.nonzero(got != np.array(want))
print("OK mesh", os.environ.get("STELLARD_VERIFY_CHECK", "bytes"))
'''


def test_point_mode_shards_over_the_mesh():
    """The consensus path's meshed XLA kernel must give oracle-equal
    verdicts in point mode too (decompress stacking happens per shard)."""
    env = dict(os.environ)
    env["STELLARD_VERIFY_CHECK"] = "point"
    r = subprocess.run(
        [sys.executable, "-u", "-c", _MESH_RUNNER % {"repo": REPO}],
        env=env,
        capture_output=True,
        text=True,
        timeout=900,
    )
    assert r.returncode == 0, (r.stdout[-2000:], r.stderr[-2000:])
    assert "OK mesh point" in r.stdout
