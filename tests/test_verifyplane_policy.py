"""Latency-aware VerifyPlane dispatch (VERDICT r2 #1b).

The plane must learn, from real measurements, when the device batch
kernel beats the threaded CPU path, and route each batch accordingly —
trickled submissions must not pay the device kernel latency.
"""

from __future__ import annotations

import time

import numpy as np
import pytest

from stellard_tpu.crypto.backend import (
    BatchVerifier,
    VerifyRequest,
    register_verifier,
)
from stellard_tpu.node.verifyplane import VerifyPlane, _LatencyModel
from stellard_tpu.protocol.keys import KeyPair


class FakeDeviceVerifier(BatchVerifier):
    """Deterministic 'device': fixed 50ms kernel latency per call."""

    name = "fake-device"
    kernel_ms = 50.0

    def __init__(self, **_):
        self.calls = []

    def verify_batch(self, batch):
        self.calls.append(len(batch))
        time.sleep(self.kernel_ms / 1000.0)
        return np.ones(len(batch), bool)


register_verifier("fake-device", FakeDeviceVerifier)


def reqs(n: int) -> list[VerifyRequest]:
    k = KeyPair.from_passphrase("vp-policy")
    m = b"\x42" * 32
    s = k.sign(m)
    return [VerifyRequest(k.public, m, s) for _ in range(n)]


class TestModel:
    def test_routing_learns_crossover(self):
        m = _LatencyModel(min_device_batch=64)
        # measured: CPU 0.1 ms/sig; device flat 50ms per call
        m.observe_cpu(100, 10.0)
        for _ in range(2):  # first device sample per bucket is warmup
            m.observe_device(256, 50.0)
            m.observe_device(4096, 55.0)
        assert not m.use_device(32)  # below floor
        assert not m.use_device(200)  # 20ms CPU < ~50ms device
        assert m.use_device(1000)  # 100ms CPU > ~50ms device
        assert m.use_device(4096)  # 410ms CPU > 55ms device

    def test_unmeasured_device_explored_then_driven_by_data(self):
        m = _LatencyModel(min_device_batch=64)
        m.observe_cpu(100, 1.0)  # very fast CPU: 0.01 ms/sig
        assert m.use_device(128)  # no device data yet: explore
        m.observe_device(128, 5000.0)  # first sample = compile: discarded
        assert m.use_device(128)  # still exploring (warm, unmeasured)
        m.observe_device(128, 50.0)  # steady-state sample
        assert not m.use_device(128)  # 1.3ms CPU beats 50ms kernel

    def test_bucket_estimates_generalize(self):
        m = _LatencyModel(min_device_batch=64)
        for _ in range(2):  # past the warmup discard
            m.observe_device(4096, 50.0)
        # unmeasured bucket borrows the nearest measurement
        assert m.expected_device_ms(256) == 50.0
        assert m.expected_device_ms(16384) == 50.0


class TestPlaneRouting:
    def test_small_batches_stay_on_cpu(self):
        plane = VerifyPlane(backend="fake-device", min_device_batch=64,
                            window_ms=1.0)
        fake: FakeDeviceVerifier = plane.verifier  # type: ignore[assignment]
        try:
            # trickle: 10 batches of 4 — all must go CPU (below floor)
            for _ in range(10):
                assert plane.verify_many(reqs(4)).all()
            assert fake.calls == []
            assert plane.cpu_batches == 10
        finally:
            plane.stop()

    def test_large_batches_move_to_device_when_it_wins(self):
        plane = VerifyPlane(backend="fake-device", min_device_batch=64,
                            window_ms=1.0)
        fake: FakeDeviceVerifier = plane.verifier  # type: ignore[assignment]
        # teach the model a slow CPU (0.5 ms/sig) without sleeping
        plane.model.observe_cpu(100, 50.0)
        # pre-warm the buckets (the first device sample per bucket is
        # treated as compile time and discarded)
        for b in (256, 64, 512):
            plane.model.observe_device(b, 0.0)
        try:
            assert plane.verify_many(reqs(256)).all()
            assert fake.calls == [256]  # 128ms CPU estimate > explore
            # model now knows device ≈ 50ms; a 64-batch (32ms CPU) goes CPU
            assert plane.verify_many(reqs(64)).all()
            assert fake.calls == [256]
            # but a 512-batch (256ms CPU) goes device
            assert plane.verify_many(reqs(512)).all()
            assert fake.calls == [256, 512]
        finally:
            plane.stop()

    def test_device_losing_everywhere_goes_all_cpu(self):
        """The r2 regression shape: device slower at every size -> after
        the exploration batch, everything routes CPU."""
        plane = VerifyPlane(backend="fake-device", min_device_batch=64,
                            window_ms=1.0)
        fake: FakeDeviceVerifier = plane.verifier  # type: ignore[assignment]
        plane.model.observe_cpu(1000, 10.0)  # fast CPU: 0.01 ms/sig
        try:
            for _ in range(6):
                plane.verify_many(reqs(256))
            # exploration hits the device at most twice (the first sample
            # is discarded as compile warmup); never again after
            assert len(fake.calls) <= 2
            assert plane.cpu_batches >= 4
        finally:
            plane.stop()

    def test_histograms_and_model_exported(self):
        plane = VerifyPlane(backend="cpu")
        try:
            plane.verify_many(reqs(8))
            j = plane.get_json()
            assert sum(j["latency_histogram_ms"]["cpu"]) == 1
            assert j["model"]["cpu_persig_ms"] is not None
        finally:
            plane.stop()

    def test_async_submit_path_unchanged(self):
        plane = VerifyPlane(backend="cpu", window_ms=1.0)
        try:
            futs = [plane.submit(r) for r in reqs(32)]
            assert all(f.result(timeout=10) for f in futs)
        finally:
            plane.stop()


class TestPrewarm:
    def test_prewarm_gates_device_until_done_then_model_is_warm(self):
        import threading

        plane = VerifyPlane(backend="fake-device", min_device_batch=64,
                            window_ms=1.0)
        fake: FakeDeviceVerifier = plane.verifier  # type: ignore[assignment]
        # hold the fake device until the live batch has been routed, so
        # the "prewarm still pending" window is deterministic
        gate = threading.Event()
        orig = fake.verify_batch

        def gated(batch):
            gate.wait(10)
            return orig(batch)

        fake.verify_batch = gated  # type: ignore[method-assign]
        try:
            t = plane.start_prewarm(sizes=(256,), rounds=2)
            # while the prewarm runs, a device-sized batch routes CPU
            assert plane.verify_many(reqs(256)).all()
            gate.set()
            t.join(timeout=30)
            assert not t.is_alive()
            assert plane._prewarm_pending is False
            # the prewarm compiled (discarded) + measured the bucket
            assert plane.model.expected_device_ms(256) is not None
            # prewarm traffic never pollutes the public counters
            assert plane.device_sigs == 0
            assert plane.verified == 256  # the one live batch above
            # prewarm calls went to the fake device directly
            assert fake.calls and all(c == 256 for c in fake.calls)
        finally:
            plane.stop()

    def test_prewarm_on_cpu_backend_is_a_noop(self):
        plane = VerifyPlane(backend="cpu")
        try:
            t = plane.start_prewarm(sizes=(64,))
            t.join(timeout=10)
            assert plane._prewarm_pending is False
        finally:
            plane.stop()


class TestBoundedReexplore:
    def test_hopeless_batches_never_reexplored(self):
        m = _LatencyModel(min_device_batch=64)
        m.observe_cpu(100, 1.0)  # 0.01 ms/sig
        for _ in range(2):
            m.observe_device(256, 500.0)  # device hopeless at this size
        # 256 sigs = 2.56ms CPU vs 500ms device: outside the 4x band,
        # so even REEXPLORE_EVERY calls never send it back to the device
        for _ in range(m.REEXPLORE_EVERY * 2 + 5):
            assert not m.use_device(256)

    def test_close_losses_are_reexplored(self):
        m = _LatencyModel(min_device_batch=64)
        m.observe_cpu(100, 30.0)  # 0.3 ms/sig
        for _ in range(2):
            m.observe_device(256, 100.0)  # 77ms CPU vs 100ms device
        hits = sum(
            m.use_device(256) for _ in range(m.REEXPLORE_EVERY + 5)
        )
        assert hits == 1  # exactly one periodic re-exploration

    def test_window_poll_does_not_advance_reexplore(self):
        m = _LatencyModel(min_device_batch=64)
        m.observe_cpu(100, 30.0)
        for _ in range(2):
            m.observe_device(256, 100.0)
        for _ in range(m.REEXPLORE_EVERY * 3):
            assert not m.use_device(256, count=False)
        assert m._since_device == 0


class TestPadPolicy:
    def test_max_policy_pads_every_chunk_to_one_shape(self, monkeypatch):
        monkeypatch.setenv("STELLARD_PAD_POLICY", "max")
        from stellard_tpu.crypto.backend import TpuVerifier

        v = TpuVerifier(min_batch=256, max_batch=16384)
        assert v._pad_size(5, 256, 16384) == 16384
        assert v._pad_size(5000, 256, 16384) == 16384

    def test_pow2_policy_keeps_proportional_buckets(self, monkeypatch):
        monkeypatch.setenv("STELLARD_PAD_POLICY", "pow2")
        from stellard_tpu.crypto.backend import TpuVerifier

        v = TpuVerifier(min_batch=256, max_batch=16384)
        assert v._pad_size(5, 256, 16384) == 256
        assert v._pad_size(5000, 256, 16384) == 8192

    def test_bad_policy_rejected(self, monkeypatch):
        monkeypatch.setenv("STELLARD_PAD_POLICY", "bogus")
        from stellard_tpu.crypto.backend import TpuVerifier

        with pytest.raises(ValueError):
            TpuVerifier()
