"""Latency-aware VerifyPlane dispatch (VERDICT r2 #1b).

The plane must learn, from real measurements, when the device batch
kernel beats the threaded CPU path, and route each batch accordingly —
trickled submissions must not pay the device kernel latency.
"""

from __future__ import annotations

import time

import numpy as np
import pytest

from stellard_tpu.crypto.backend import (
    BatchVerifier,
    VerifyRequest,
    register_verifier,
)
from stellard_tpu.node.verifyplane import VerifyPlane, _LatencyModel
from stellard_tpu.protocol.keys import KeyPair


class FakeDeviceVerifier(BatchVerifier):
    """Deterministic 'device': fixed 50ms kernel latency per call."""

    name = "fake-device"
    kernel_ms = 50.0

    def __init__(self, **_):
        self.calls = []

    def verify_batch(self, batch):
        self.calls.append(len(batch))
        time.sleep(self.kernel_ms / 1000.0)
        return np.ones(len(batch), bool)


register_verifier("fake-device", FakeDeviceVerifier)


def reqs(n: int) -> list[VerifyRequest]:
    k = KeyPair.from_passphrase("vp-policy")
    m = b"\x42" * 32
    s = k.sign(m)
    return [VerifyRequest(k.public, m, s) for _ in range(n)]


class TestModel:
    def test_routing_learns_crossover(self):
        m = _LatencyModel(min_device_batch=64)
        # measured: CPU 0.1 ms/sig; device flat 50ms per call
        m.observe_cpu(100, 10.0)
        for _ in range(2):  # first device sample per bucket is warmup
            m.observe_device(256, 50.0)
            m.observe_device(4096, 55.0)
        assert not m.use_device(32)  # below floor
        assert not m.use_device(200)  # 20ms CPU < ~50ms device
        assert m.use_device(1000)  # 100ms CPU > ~50ms device
        assert m.use_device(4096)  # 410ms CPU > 55ms device

    def test_unmeasured_device_explored_then_driven_by_data(self):
        m = _LatencyModel(min_device_batch=64)
        m.observe_cpu(100, 1.0)  # very fast CPU: 0.01 ms/sig
        assert m.use_device(128)  # no device data yet: explore
        m.observe_device(128, 5000.0)  # first sample = compile: discarded
        assert m.use_device(128)  # still exploring (warm, unmeasured)
        m.observe_device(128, 50.0)  # steady-state sample
        assert not m.use_device(128)  # 1.3ms CPU beats 50ms kernel

    def test_bucket_estimates_generalize(self):
        m = _LatencyModel(min_device_batch=64)
        for _ in range(2):  # past the warmup discard
            m.observe_device(4096, 50.0)
        # unmeasured bucket borrows the nearest measurement
        assert m.expected_device_ms(256) == 50.0
        assert m.expected_device_ms(16384) == 50.0


class TestPlaneRouting:
    def test_small_batches_stay_on_cpu(self):
        plane = VerifyPlane(backend="fake-device", min_device_batch=64,
                            window_ms=1.0)
        fake: FakeDeviceVerifier = plane.verifier  # type: ignore[assignment]
        try:
            # trickle: 10 batches of 4 — all must go CPU (below floor)
            for _ in range(10):
                assert plane.verify_many(reqs(4)).all()
            assert fake.calls == []
            assert plane.cpu_batches == 10
        finally:
            plane.stop()

    def test_large_batches_move_to_device_when_it_wins(self):
        plane = VerifyPlane(backend="fake-device", min_device_batch=64,
                            window_ms=1.0)
        fake: FakeDeviceVerifier = plane.verifier  # type: ignore[assignment]
        # teach the model a slow CPU (0.5 ms/sig) without sleeping
        plane.model.observe_cpu(100, 50.0)
        # pre-warm the buckets (the first device sample per bucket is
        # treated as compile time and discarded)
        for b in (256, 64, 512):
            plane.model.observe_device(b, 0.0)
        try:
            assert plane.verify_many(reqs(256)).all()
            assert fake.calls == [256]  # 128ms CPU estimate > explore
            # model now knows device ≈ 50ms; a 64-batch (32ms CPU) goes CPU
            assert plane.verify_many(reqs(64)).all()
            assert fake.calls == [256]
            # but a 512-batch (256ms CPU) goes device
            assert plane.verify_many(reqs(512)).all()
            assert fake.calls == [256, 512]
        finally:
            plane.stop()

    def test_device_losing_everywhere_goes_all_cpu(self):
        """The r2 regression shape: device slower at every size -> after
        the exploration batch, everything routes CPU."""
        plane = VerifyPlane(backend="fake-device", min_device_batch=64,
                            window_ms=1.0)
        fake: FakeDeviceVerifier = plane.verifier  # type: ignore[assignment]
        plane.model.observe_cpu(1000, 10.0)  # fast CPU: 0.01 ms/sig
        try:
            for _ in range(6):
                plane.verify_many(reqs(256))
            # exploration hits the device at most twice (the first sample
            # is discarded as compile warmup); never again after
            assert len(fake.calls) <= 2
            assert plane.cpu_batches >= 4
        finally:
            plane.stop()

    def test_histograms_and_model_exported(self):
        plane = VerifyPlane(backend="cpu")
        try:
            plane.verify_many(reqs(8))
            j = plane.get_json()
            assert sum(j["latency_histogram_ms"]["cpu"]) == 1
            assert j["model"]["cpu_persig_ms"] is not None
        finally:
            plane.stop()

    def test_async_submit_path_unchanged(self):
        plane = VerifyPlane(backend="cpu", window_ms=1.0)
        try:
            futs = [plane.submit(r) for r in reqs(32)]
            assert all(f.result(timeout=10) for f in futs)
        finally:
            plane.stop()
