"""Amendment + fee voting through real consensus rounds.

Reference behavior covered (SURVEY §2.5 AmendmentTable / FeeVote):
- validators carry amendment votes and fee targets in their validations
  (AmendmentTableImpl::doValidation, FeeVoteImpl::doValidation),
- on a flag-ledger boundary the winning votes become ttAMENDMENT/ttFEE
  pseudo-transactions in the next initial position
  (doVoting, LedgerConsensus.cpp:1033-1038),
- the pseudo-txs apply through the Change transactors, so the amendment
  lands in ltAMENDMENTS and the fee schedule actually changes — on every
  validator identically.
"""

from __future__ import annotations

import hashlib

from stellard_tpu.consensus.validation import STValidation
from stellard_tpu.consensus.voting import (
    AmendmentTable,
    FeeVote,
    VotingBox,
    make_amendment_tx,
)
from stellard_tpu.overlay.simnet import SimNet
from stellard_tpu.protocol.sfields import sfAmendments
from stellard_tpu.state import indexes

AMENDMENT_X = hashlib.sha256(b"featureX").digest()
AMENDMENT_Y = hashlib.sha256(b"featureY").digest()


def make_box(flag_interval=4, support=(AMENDMENT_X,), base_fee=None):
    at = AmendmentTable(majority_time=0)
    for a in support:
        at.add_known(a, supported=True)
    fv = None
    if base_fee is not None:
        fv = FeeVote(target_base_fee=base_fee)
    return VotingBox(amendments=at, fees=fv, flag_interval=flag_interval)


class TestUnits:
    def test_amendment_majority_tracking(self):
        at = AmendmentTable(majority_time=100, majority_fraction=204)
        at.add_known(AMENDMENT_X)

        def vals(n_for, n_total):
            out = []
            for i in range(n_total):
                v = STValidation.build(
                    ledger_hash=b"\x01" * 32,
                    signing_time=0,
                    amendments=[AMENDMENT_X] if i < n_for else None,
                )
                v.trusted = True
                out.append(v)
            return out

        # 2 of 4 voters: below the ~80% line — no majority recorded
        assert at.do_voting(1000, vals(2, 4)) == []
        assert AMENDMENT_X not in at.majorities
        # 4 of 4: majority starts, but must HOLD for majority_time
        assert at.do_voting(1000, vals(4, 4)) == []
        assert at.do_voting(1050, vals(4, 4)) == []
        txs = at.do_voting(1101, vals(4, 4))
        assert len(txs) == 1 and txs[0].txid() == make_amendment_tx(AMENDMENT_X).txid()
        # a lapse resets the clock
        at2 = AmendmentTable(majority_time=100)
        at2.add_known(AMENDMENT_Y)
        at2.do_voting(1000, vals(4, 4))
        at2.do_voting(1050, vals(0, 4))  # lost majority
        assert at2.do_voting(1101, vals(4, 4)) == []

    def test_fee_plurality(self):
        fv = FeeVote(target_base_fee=15)

        class L:
            base_fee = 10
            reference_fee_units = 10
            reserve_base = 200_000_000
            reserve_increment = 50_000_000

        def vals(fees):
            out = []
            for f in fees:
                v = STValidation.build(
                    ledger_hash=b"\x01" * 32, signing_time=0, base_fee=f
                )
                v.trusted = True
                out.append(v)
            return out

        # majority votes 15 -> SetFee pseudo-tx at 15
        txs = fv.do_voting(L(), vals([15, 15, 15, None]))
        assert len(txs) == 1
        from stellard_tpu.protocol.sfields import sfBaseFee

        assert txs[0].obj[sfBaseFee] == 15
        # split vote: current value wins by incumbent bias -> no change
        assert fv.do_voting(L(), vals([15, 15, None, None])) == []


class TestByzantineVoting:
    def test_replayed_validation_single_voice_in_amendment_tally(self):
        """A byzantine validator replaying its amendment-voting
        validation (and equivocating its vote) gets ONE voice: the
        voting inputs come from ValidationsStore.validations_for, which
        keys per signer, so replays and re-votes collapse to the latest
        statement instead of stacking toward the 80% line."""
        from stellard_tpu.consensus.validations import ValidationsStore
        from stellard_tpu.protocol.keys import KeyPair

        keys = [KeyPair.from_passphrase(f"vote-{i}") for i in range(4)]
        trusted = {k.public for k in keys}
        now = [10_000]
        store = ValidationsStore(lambda pk: pk in trusted,
                                 lambda: now[0])
        noted = []
        store.note_byzantine = lambda kind, **kw: noted.append(kind)
        parent = b"\x42" * 32
        # one honest YES vote; the byzantine node replays ITS yes vote
        # three times and then re-votes with a different amendment set
        honest = STValidation.build(parent, signing_time=now[0],
                                    amendments=[AMENDMENT_X])
        honest.sign(keys[1])
        store.add(honest)
        byz = STValidation.build(parent, signing_time=now[0],
                                 amendments=[AMENDMENT_X])
        byz.sign(keys[0])
        for _ in range(3):
            store.add(STValidation.from_bytes(byz.serialize()))
        revote = STValidation.build(parent, signing_time=now[0] + 1,
                                    amendments=[AMENDMENT_X, AMENDMENT_Y])
        revote.sign(keys[0])
        store.add(revote)
        vals = store.validations_for(parent)
        assert len(vals) == 2  # one entry per signer, not five
        assert "duplicate_validation" in noted
        # the byzantine signer's LATEST statement is its one voice
        by_signer = {v.signer: v for v in vals}
        assert set(by_signer[keys[0].public].amendments) == {
            AMENDMENT_X, AMENDMENT_Y
        }


class TestConsensusVoting:
    def test_amendment_and_fee_enacted_via_consensus(self):
        net = SimNet(
            4,
            voting_factory=lambda i: make_box(
                flag_interval=4, support=(AMENDMENT_X,), base_fee=15
            ),
        )
        net.start()
        # run well past the first flag boundary (seq 4) + enactment (seq 5)
        assert net.run_until(lambda: net.all_validated_at_least(6), 120)
        for v in net.validators:
            led = v.node.lm.validated
            sle = led.read_entry(indexes.amendment_index())
            assert sle is not None, "ltAMENDMENTS missing"
            assert AMENDMENT_X in list(sle.get(sfAmendments, []))
            assert led.base_fee == 15
            # voting box sees it enabled now -> no longer voted for
            assert v.node.voting.amendments.do_validation() is None
        # no forks anywhere along the way
        for seq in range(2, 6):
            assert len(net.validated_hashes_at(seq)) == 1

    def test_vetoed_amendment_never_enacts(self):
        def factory(i):
            box = make_box(flag_interval=4, support=(AMENDMENT_X,))
            if i == 0:
                box.amendments.veto(AMENDMENT_X)
            return box

        net = SimNet(4, voting_factory=factory)
        net.start()
        assert net.run_until(lambda: net.all_validated_at_least(6), 120)
        # 3 of 4 vote for it — below the 204/256 (~80%) threshold, so the
        # ledger stays clean and there is no fork
        for v in net.validators:
            led = v.node.lm.validated
            sle = led.read_entry(indexes.amendment_index())
            enabled = list(sle.get(sfAmendments, [])) if sle else []
            assert AMENDMENT_X not in enabled
        for seq in range(2, 6):
            assert len(net.validated_hashes_at(seq)) == 1
