"""Protobuf wire-format compatibility of the overlay schema.

Asserts the properties SURVEY §5 names as the compatibility target
(reference: src/ripple/proto/ripple.proto + Message.cpp framing):
ripple.proto message-type numbers, ripple.proto field numbers encoded in
genuine proto2 wire format, unknown-field forward compatibility, and
malformed-payload rejection.
"""

from __future__ import annotations

import pytest

from stellard_tpu.overlay.proto import Encoder, first_bytes, first_int, parse
from stellard_tpu.overlay import wire as W


H32 = bytes(range(32))


class TestCodec:
    def test_varint_roundtrip(self):
        for v in (0, 1, 127, 128, 300, 2**32 - 1, 2**63):
            buf = Encoder().varint(7, v).data()
            assert first_int(parse(buf), 7) == v

    def test_unknown_fields_are_skipped(self):
        # forward compatibility: a newer peer adds field 99; we must parse
        buf = (
            Encoder()
            .varint(1, 5)
            .blob(99, b"from-the-future")
            .fixed32(98, 7)
            .fixed64(97, 9)
            .data()
        )
        f = parse(buf)
        assert first_int(f, 1) == 5
        assert first_bytes(f, 99) == b"from-the-future"

    @pytest.mark.parametrize(
        "bad",
        [
            b"\x08",  # tag then truncated varint
            b"\x12\x05ab",  # length-delimited longer than buffer
            b"\x00\x01",  # field number 0
            b"\x0b",  # wire type 3 (group) unsupported
            b"\xff\xff\xff\xff\xff\xff\xff\xff\xff\xff\x01",  # tag overflow
        ],
    )
    def test_malformed_rejected(self, bad):
        with pytest.raises(ValueError):
            parse(bad)


class TestRippleProtoNumbers:
    """Wire ids and field numbers must match ripple.proto exactly."""

    def test_message_type_numbers(self):
        # ripple.proto MessageType enum
        assert W.MessageType.HELLO == 1
        assert W.MessageType.PING == 3
        assert W.MessageType.CLUSTER == 5
        assert W.MessageType.ENDPOINTS == 15
        assert W.MessageType.TRANSACTION == 30
        assert W.MessageType.GET_LEDGER == 31
        assert W.MessageType.LEDGER_DATA == 32
        assert W.MessageType.PROPOSE_SET == 33
        assert W.MessageType.STATUS_CHANGE == 34
        assert W.MessageType.HAVE_TX_SET == 35
        assert W.MessageType.VALIDATION == 41
        assert W.MessageType.GET_OBJECTS == 42

    def test_hello_field_numbers(self):
        m = W.Hello(1, 99, b"\x02" * 32, b"\x03" * 64, 7, H32, 5123)
        f = parse(W.encode_message(m))
        assert first_int(f, 1) == 1  # protoVersion
        assert first_int(f, 2) == 1  # protoVersionMin
        assert first_bytes(f, 3) == b"\x02" * 32  # nodePublic
        assert first_bytes(f, 4) == b"\x03" * 64  # nodeProof
        assert first_int(f, 6) == 99  # netTime
        assert first_int(f, 7) == 5123  # ipv4Port
        assert first_int(f, 8) == 7  # ledgerIndex
        assert first_bytes(f, 9) == H32  # ledgerClosed

    def test_propose_field_numbers(self):
        m = W.ProposeSet(4, 777, b"\x01" * 32, b"\x02" * 32, b"\x03" * 32,
                         b"\x04" * 64)
        f = parse(W.encode_message(m))
        assert first_int(f, 1) == 4  # proposeSeq
        assert first_bytes(f, 2) == b"\x02" * 32  # currentTxHash
        assert first_bytes(f, 3) == b"\x03" * 32  # nodePubKey
        assert first_int(f, 4) == 777  # closeTime
        assert first_bytes(f, 5) == b"\x04" * 64  # signature
        assert first_bytes(f, 6) == b"\x01" * 32  # previousledger

    def test_transaction_carries_status(self):
        f = parse(W.encode_message(W.TxMessage(b"rawtx")))
        assert first_bytes(f, 1) == b"rawtx"  # rawTransaction
        assert first_int(f, 2) == 2  # status tsCURRENT (required field)

    def test_txset_rides_get_ledger_li_ts_candidate(self):
        # reference acquires candidate sets via TMGetLedger/TMLedgerData
        mt, _ = W._ENCODERS[W.GetTxSet]
        assert mt == W.MessageType.GET_LEDGER
        f = parse(W.encode_message(W.GetTxSet(H32)))
        assert first_int(f, 1) == 3  # itype liTS_CANDIDATE
        assert first_bytes(f, 3) == H32  # ledgerHash slot

        data = W.TxSetData(H32, [b"t1", b"t2"])
        f = parse(W.encode_message(data))
        assert first_int(f, 3) == 3  # type liTS_CANDIDATE
        nodes = [parse(sub) for sub in f[4]]
        assert [first_bytes(nf, 1) for nf in nodes] == [b"t1", b"t2"]

    def test_endpoints_nested_ipv4(self):
        m = W.Endpoints([("10.1.2.3", 51235, 2)])
        f = parse(W.encode_message(m))
        assert first_int(f, 1) == 1  # version
        ep = parse(f[2][0])
        ip = parse(first_bytes(ep, 1))
        assert first_int(ip, 1) == (10 << 24) | (1 << 16) | (2 << 8) | 3
        assert first_int(ip, 2) == 51235
        assert first_int(ep, 2) == 2  # hops

    def test_get_objects_query_flag_dispatch(self):
        q = W.decode_message(42, W.encode_message(W.GetObjects([H32])))
        assert isinstance(q, W.GetObjects) and q.hashes == [H32]
        r = W.decode_message(
            42, W.encode_message(W.ObjectsData([(H32, b"blob")]))
        )
        assert isinstance(r, W.ObjectsData) and r.objects == [(H32, b"blob")]


class TestRoundTrips:
    def test_all_messages_roundtrip(self):
        msgs = [
            W.Hello(1, 99, b"\x02" * 32, b"\x03" * 64, 7, H32, 1234),
            W.Ping(False, 3),
            W.Ping(True, 4),
            W.TxMessage(b"tx-blob"),
            W.ProposeSet(1, 2, b"\x01" * 32, b"\x02" * 32, b"\x03" * 32,
                         b"\x04" * 64),
            W.ValidationMessage(b"val-blob"),
            W.HaveTxSet(H32),
            W.GetTxSet(H32),
            W.TxSetData(H32, [b"a", b"bb"]),
            W.GetLedger(H32, 0, 2, [b"\x00", b"\x01\x23"]),
            W.LedgerData(H32, 9, 1, [(b"\x00", b"blob")]),
            W.StatusChange(4, 12, H32, 555),
            W.Endpoints([("127.0.0.1", 1024, 0), ("192.168.0.9", 2, 7)]),
            W.GetObjects([H32, bytes(32)]),
            W.ObjectsData([(H32, b"payload")]),
        ]
        reader = W.FrameReader()
        stream = b"".join(W.frame(m) for m in msgs)
        # feed in awkward chunk sizes to exercise incremental framing
        got = []
        for i in range(0, len(stream), 7):
            got.extend(reader.feed(stream[i : i + 7]))
        assert got == msgs

    def test_cluster_roundtrip(self):
        from stellard_tpu.protocol.keys import KeyPair

        pk = KeyPair.from_passphrase("cluster-node").public
        pk2 = KeyPair.from_passphrase("cluster-node-2").public
        m = W.ClusterStatus(pk, 512, 777)
        out = W.decode_message(5, W.encode_message(m))
        assert out == W.ClusterUpdate([m])
        # clusterNodes is `repeated`: multi-node and node-less TMClusters
        # are schema-legal and must decode, not disconnect the peer
        multi = W.ClusterUpdate([m, W.ClusterStatus(pk2, 256, 778)])
        assert W.decode_message(5, W.encode_message(multi)) == multi
        assert W.decode_message(5, b"") == W.ClusterUpdate([])

    def test_unknown_message_types_are_skipped(self):
        # a full-ripple.proto peer sends types outside our subset
        # (e.g. mtERROR_MSG=2, mtPROOFOFWORK=4): the frame is consumed
        # and the stream continues — never an error/disconnect
        assert W.decode_message(2, b"\x0a\x03abc") is None
        reader = W.FrameReader()
        unknown = (5).to_bytes(4, "big") + (4).to_bytes(2, "big") + b"\x08\x01abc"
        got = reader.feed(unknown + W.frame(W.Ping(False, 9)))
        assert got == [W.Ping(False, 9)]
        # ...but a type outside the schema entirely is a violation (the
        # resource plane charges the sender), not forward compatibility
        with pytest.raises(ValueError):
            W.decode_message(999, b"junk")


class TestCodecFuzz:
    def test_random_bytes_never_crash_the_parser(self):
        """parse() on arbitrary bytes either returns a field dict or
        raises ValueError — no other exception class may escape (the
        overlay charges-and-drops on ValueError; anything else would
        kill the session thread)."""
        import random

        rng = random.Random(1234)
        for _ in range(2000):
            buf = bytes(rng.getrandbits(8) for _ in range(rng.randrange(0, 64)))
            try:
                parse(buf)
            except ValueError:
                pass

    def test_truncated_real_messages_never_crash_decoders(self):
        import pytest as _pytest

        msgs = [
            W.Hello(1, 99, b"\x02" * 32, b"\x03" * 64, 7, H32, 1234),
            W.ProposeSet(1, 2, b"\x01" * 32, b"\x02" * 32, b"\x03" * 32,
                         b"\x04" * 64),
            W.LedgerData(H32, 9, 1, [(b"\x00", b"blob")]),
            W.Endpoints([("127.0.0.1", 1024, 0)]),
        ]
        for m in msgs:
            mt, enc = W._ENCODERS[type(m)]
            payload = enc(m)
            for cut in range(len(payload)):
                try:
                    W.decode_message(int(mt), payload[:cut])
                except ValueError:
                    continue
