"""Tier-1 archive-tier smoke: the shard distribution network as a gate.

Boots a LEADER (networked solo validator, quorum=1) with online
deletion + history shards on, floods it until at least two shard files
are sealed and the SQL retain floor has climbed past them — deep
history now exists ONLY in cold storage — then exercises the archive
tier end to end over real TCP, in two phases:

- Phase A (hostile upstream): an archive node boots cold while the
  leader's segment source is wrapped by a corrupting proxy that flips
  one byte in every whole-shard-file transfer. The archive's backfill
  must REJECT every poisoned image at the ``verify_shard_blob`` gate,
  condemn the peer (resource-charged on its overlay endpoint AND
  excluded from the segment-peer candidate set), and retain ZERO
  hostile bytes — no shard file ever touches the archive directory.
- Phase B (honest restart): the corruption is removed and a fresh
  archive boots against the SAME (still-empty) archive directory. It
  must backfill >= 2 sealed shards over the wire from cold start,
  ingest the validated tail like a follower (zero consensus rounds),
  and serve deep-history RPCs BELOW the leader's retain floor —
  ``account_tx`` / ``tx`` / ``ledger`` — whose bytes are compared
  row-for-row against the leader's sealed shard contents (the
  verify-checked source of truth). The forever tier of the result
  cache must take hits on repeated immutable-window queries.

Runtime: ~60-120s (clock_speed-accelerated consensus).

Usage: python tools/archivesmoke.py
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SPEED = 5.0


def fail(msg: str) -> None:
    print(f"ARCHIVE SMOKE FAILED: {msg}", file=sys.stderr)
    raise SystemExit(2)


class _CorruptingSource:
    """Hostile-peer stand-in: delegates to the leader's real segment
    source but flips one byte in the first chunk of every whole-shard-
    FILE transfer (ids at or above SHARD_FILE_BASE). Manifests and live
    tail segments pass through honestly, so only the deep-history
    backfill sees poisoned bytes — exactly the garbage-peer scenario
    the verify gate + condemnation path exists for."""

    def __init__(self, inner):
        self._inner = inner
        self.corrupted = 0

    def segments(self):
        return self._inner.segments()

    def fetch_segment(self, seg_id, offset=0, length=None):
        from stellard_tpu.nodestore.shards import SHARD_FILE_BASE

        got = self._inner.fetch_segment(seg_id, offset=offset,
                                        length=length)
        if got is None or seg_id < SHARD_FILE_BASE or offset != 0:
            return got
        meta, data = got
        b = bytearray(data)
        if len(b) > 40:
            b[40] ^= 0xFF  # inside the header's reserved area: CRC breaks
            self.corrupted += 1
        return meta, bytes(b)


def main() -> None:
    import tempfile

    import jax

    jax.config.update("jax_platforms", "cpu")

    from stellard_tpu.node.config import Config
    from stellard_tpu.node.node import Node
    from stellard_tpu.protocol.formats import TxType
    from stellard_tpu.protocol.keys import KeyPair, encode_account_id
    from stellard_tpu.protocol.sfields import sfAmount, sfDestination
    from stellard_tpu.protocol.stamount import STAmount
    from stellard_tpu.protocol.sttx import SerializedTransaction
    from stellard_tpu.testkit.tcpnet import free_ports, rpc, wait_until

    tmp = tempfile.mkdtemp(prefix="archivesmoke-")
    leader_peer, arch_a_peer, arch_b_peer = free_ports(3)
    val_key = KeyPair.from_passphrase("archivesmoke-leader")
    archive_dir = os.path.join(tmp, "archive-shards")

    leader = Node(Config(
        standalone=False,
        signature_backend="cpu",
        node_db_type="segstore",
        node_db_path=os.path.join(tmp, "leader-ns"),
        database_path=os.path.join(tmp, "leader.db"),
        node_db_segment_mb=1,
        node_db_online_delete=4,
        node_db_online_delete_interval=2,
        node_db_shards="1",
        validation_seed=val_key.human_seed,
        validation_quorum=1,
        peer_port=leader_peer,
        clock_speed=SPEED,
        rpc_port=0,
    )).setup().serve()

    arch = None
    try:
        # phase 0: flood the leader until >= 2 shards are sealed and the
        # retain floor has climbed past them — from here on, the ONLY
        # place the deep rows exist is the leader's cold shard files
        master = leader.master_keys
        dests = [KeyPair.from_passphrase(f"asmoke-{i}").account_id
                 for i in range(8)]
        acked = threading.Semaphore(0)

        def cb(_tx, _ter, _applied):
            acked.release()

        next_seq = 1

        def submit_batch(n: int) -> None:
            nonlocal next_seq
            for _ in range(n):
                tx = SerializedTransaction.build(
                    TxType.ttPAYMENT, master.account_id, next_seq, 10,
                    {sfAmount: STAmount.from_drops(250_000_000),
                     sfDestination: dests[next_seq % len(dests)]},
                )
                tx.sign(master)
                leader.ops.submit_transaction(tx, cb)
                next_seq += 1
            for _ in range(n):
                acked.acquire()

        def sealed_deep() -> bool:
            shs = leader.shardstore.shards()
            return (len(shs) >= 2
                    and leader.txdb.retain_floor > shs[1]["hi"])

        t_end = time.monotonic() + 180
        while not sealed_deep():
            if time.monotonic() > t_end:
                fail(f"leader never sealed 2 deep shards "
                     f"(shards={leader.shardstore.shards()}, "
                     f"floor={leader.txdb.retain_floor})")
            submit_batch(10)
            time.sleep(0.2)

        lshards = leader.shardstore.shards()
        floor = leader.txdb.retain_floor
        deep = [sh for sh in lshards if sh["hi"] < floor][:2]
        if len(deep) < 2:
            fail(f"sealed shards not below the floor: {lshards}, "
                 f"floor={floor}")

        def archive_cfg(name: str, port: int) -> Config:
            return Config(
                standalone=False,
                node_mode="archive",
                signature_backend="cpu",
                node_db_type="segstore",
                node_db_path=os.path.join(tmp, f"{name}-ns"),
                database_path=os.path.join(tmp, f"{name}.db"),
                archive_path=archive_dir,
                archive_rescan_s=2.0,
                validators=[val_key.human_node_public],
                validation_quorum=1,
                peer_port=port,
                node_upstream=[f"127.0.0.1 {leader_peer}"],
                clock_speed=SPEED,
                rpc_port=0,
            )

        # phase A: poison every whole-shard-file transfer at the source
        lvn = leader.overlay.node
        honest_src = lvn.segment_source
        proxy = _CorruptingSource(honest_src)
        lvn.segment_source = proxy

        arch = Node(archive_cfg("arch-a", arch_a_peer)).setup().serve()
        sb_a = arch.overlay.node.shard_backfill
        if sb_a is None:
            fail("archive node booted without a shard backfill")

        if not wait_until(
            lambda: sb_a.get_json()["import_rejects"] >= 1
            and sb_a.get_json()["garbage_peers"] >= 1, 120, 0.2,
        ):
            fail(f"hostile upstream never condemned: {sb_a.get_json()} "
                 f"(proxy corrupted {proxy.corrupted} chunks)")
        if proxy.corrupted < 1:
            fail("anti-vacuity: the corrupting proxy never fired")

        # charged + excluded: the garbage-segment charge lands on the
        # leader's endpoint in the ARCHIVE's resource table, pushing it
        # to WARN — segment_peers() then refuses it the bulk-transfer
        # privilege (the balance decays, so check promptly)
        charged = False
        excluded = False
        t_end = time.monotonic() + 30
        while time.monotonic() < t_end and not (charged and excluded):
            with arch.overlay._peers_lock:
                remotes = [p.remote for p in arch.overlay.peers.values()]
            for r in remotes:
                if arch.overlay.resources.balance(r) > 0:
                    charged = True
            if not arch.overlay.segment_peers() and remotes:
                excluded = True
            if not remotes:
                # one charge short of DROP normally, but repeated
                # garbage rounds can stack to a disconnect — that IS
                # charged-and-excluded
                charged = excluded = True
            time.sleep(0.05)
        if not charged or not excluded:
            fail(f"condemned peer not charged+excluded "
                 f"(charged={charged}, excluded={excluded}, "
                 f"backfill={sb_a.get_json()})")

        # zero hostile bytes retained: no shard file ever landed
        aj = sb_a.get_json()
        if aj["imported"] != 0:
            fail(f"archive imported a poisoned shard: {aj}")
        if arch.shardstore.shards():
            fail(f"hostile bytes installed: {arch.shardstore.shards()}")
        leftovers = [f for f in os.listdir(archive_dir)
                     if f.endswith(".shard")]
        if leftovers:
            fail(f"hostile shard files retained on disk: {leftovers}")
        phase_a = {k: aj[k] for k in
                   ("import_rejects", "garbage_peers", "imported")}
        arch.stop()
        arch = None

        # phase B: honest leader, fresh archive process, SAME cold
        # archive directory — backfill >= 2 shards over the wire
        lvn.segment_source = honest_src
        arch = Node(archive_cfg("arch-b", arch_b_peer)).setup().serve()
        vn = arch.overlay.node
        sb = vn.shard_backfill

        if not wait_until(
            lambda: sb.get_json()["imported"] >= 2
            and arch.shardstore.contiguous_floor() >= deep[1]["hi"],
            180, 0.2,
        ):
            fail(f"honest backfill incomplete: {sb.get_json()}, "
                 f"archive shards={arch.shardstore.shards()}")
        bj = sb.get_json()
        if bj["garbage_peers"] != 0:
            fail(f"honest leader condemned in phase B: {bj}")
        if arch.read_plane.archive_floor <= 0:
            fail("verified floor never published to the read plane")

        # tail ingest: the archive follows the live chain like a
        # follower and never runs consensus
        def validated_of(node):
            v = node.ledger_master.validated
            return v.seq if v is not None else 0

        submit_batch(10)
        target = validated_of(leader)
        if not wait_until(lambda: validated_of(arch) >= target, 120, 0.5):
            fail(f"archive tail ingest stalled "
                 f"(arch={validated_of(arch)}, leader={target})")
        if vn.rounds_completed != 0:
            fail(f"archive completed {vn.rounds_completed} consensus "
                 f"rounds — the archive tier must never close")

        # deep-history serving, byte-matched against the leader's
        # sealed shard contents (below the leader's retain floor, these
        # rows exist nowhere else)
        aport = arch.http_server.port
        rows_checked = 0
        for sh in deep:
            sid = sh["id"]
            by_acct: dict = {}
            for acct, lseq, tseq, txid in leader.shardstore.acct_rows(sid):
                by_acct.setdefault(acct, []).append((lseq, tseq, txid))
            for acct, ents in sorted(by_acct.items()):
                ents.sort()
                r = rpc(aport, "account_tx", {
                    "account": encode_account_id(acct),
                    "ledger_index_min": sh["lo"],
                    "ledger_index_max": sh["hi"],
                    "forward": True, "binary": True, "limit": 500,
                })
                if r.get("status") != "success":
                    fail(f"deep account_tx refused below the leader "
                         f"floor {floor}: {r}")
                got = r["transactions"]
                if len(got) != len(ents):
                    fail(f"deep account_tx row count mismatch shard "
                         f"{sid}: served {len(got)}, shard has "
                         f"{len(ents)}")
                for entry, (lseq, _tseq, txid) in zip(got, ents):
                    want = leader.shardstore.tx_blob(sid, txid)
                    if want is None:
                        fail(f"shard {sid} lost txid {txid.hex()}")
                    if entry["tx_blob"] != want[0].hex().upper():
                        fail(f"deep tx bytes diverge from sealed shard "
                             f"{sid} at seq {lseq}: {txid.hex()}")
                    if int(entry["ledger_index"]) != lseq:
                        fail(f"deep row seq mismatch: "
                             f"{entry['ledger_index']} != {lseq}")
                    rows_checked += 1
            # the shard's anchor header must resolve through the deep
            # `ledger` door with the sealed first-ledger hash
            r = rpc(aport, "ledger", {"ledger_index": sh["lo"]})
            if r.get("status") != "success":
                fail(f"deep ledger {sh['lo']} refused: {r}")
            if r["ledger"]["hash"] != sh["first_hash"].upper():
                fail(f"deep ledger hash diverges at seq {sh['lo']}: "
                     f"{r['ledger']['hash']} != shard "
                     f"{sh['first_hash'].upper()}")
        if rows_checked < 1:
            fail("anti-vacuity: the sealed shards held zero account "
                 "rows — the byte-match leg never ran")

        # one deep tx by hash, byte-anchored via its ledger seq
        sid0 = deep[0]["id"]
        arows = leader.shardstore.acct_rows(sid0)
        if arows:
            _acct, lseq, _tseq, txid = arows[0]
            r = rpc(aport, "tx", {"transaction": txid.hex()})
            if r.get("status") != "success":
                fail(f"deep tx {txid.hex()} refused: {r}")
            if int(r["ledger_index"]) != lseq:
                fail(f"deep tx seq mismatch: {r['ledger_index']} != "
                     f"{lseq}")

        # the forever tier: an immutable below-floor window must hit
        # across repeats (it was admitted during the sweep above)
        probe = {
            "account": master.human_account_id,
            "ledger_index_min": deep[0]["lo"],
            "ledger_index_max": deep[0]["hi"],
            "forward": True, "binary": True, "limit": 500,
        }
        rpc(aport, "account_tx", probe)
        h0 = arch.read_cache.get_json()["forever_hits"]
        rpc(aport, "account_tx", probe)
        cj = arch.read_cache.get_json()
        if cj["forever_entries"] <= 0 or cj["forever_hits"] <= h0:
            fail(f"forever cache never engaged on an immutable deep "
                 f"window: {cj}")

        print(json.dumps({
            "archive_smoke": "ok",
            "leader_floor": floor,
            "deep_shards": [(sh["id"], sh["lo"], sh["hi"])
                            for sh in deep],
            "phase_a_hostile": phase_a,
            "proxy_corrupted_chunks": proxy.corrupted,
            "phase_b_backfill": {
                k: bj[k] for k in ("imported", "duplicates", "requests",
                                   "bytes", "garbage_peers")
            },
            "verified_floor": arch.read_plane.archive_floor,
            "deep_rows_byte_checked": rows_checked,
            "forever_cache": cj,
            "ledgers_ingested": vn.ledgers_ingested,
        }), flush=True)
    finally:
        if arch is not None:
            arch.stop()
        leader.stop()
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    main()
