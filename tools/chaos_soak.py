"""Randomized chaos soak over a REAL 4-validator TCP+TLS net.

The standalone, longer-running sibling of
tests/test_multiproc_net.py::test_load_restart_convergence (the r4
build-time soak that surfaced the fork-repair fixes): continuous RPC
payment load while a validator is killed and revived every ~45s
(rotating victims), for `minutes` (default 12). Ends by asserting every
validator is quorum-validated on one advancing chain with one hash, and
prints a JSON summary line. Validators are always torn down, even on a
failed run.

Usage: python tools/chaos_soak.py [minutes] [> CHAOS_SOAK.log]
"""

from __future__ import annotations

import json
import os
import random
import subprocess
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from netlab import (  # noqa: E402
    free_ports,
    rpc,
    spawn_validator,
    validator_config,
)
from stellard_tpu.protocol.keys import KeyPair  # noqa: E402

MINUTES = float(sys.argv[1]) if len(sys.argv) > 1 else 12.0
N = 4


def main() -> None:
    tmp = tempfile.mkdtemp(prefix="chaos-")
    ports = free_ports(2 * N)
    peer_ports, rpc_ports = ports[:N], ports[N:]
    keys = [KeyPair.from_passphrase(f"chaos-val-{i}") for i in range(N)]
    cfg_paths = []
    for i in range(N):
        p = os.path.join(tmp, f"v{i}.cfg")
        open(p, "w").write(
            validator_config(i, keys, peer_ports, rpc_ports[i])
        )
        cfg_paths.append(p)

    procs: list = [None] * N

    def respawn(i):
        procs[i] = spawn_validator(cfg_paths[i])

    for i in range(N):
        respawn(i)

    try:
        _run(procs, respawn, rpc_ports)
    finally:
        # ALWAYS tear the net down — a failed run must not leak four
        # validator processes holding ports and CPU
        for p in procs:
            if p is None:
                continue
            p.terminate()
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()


def _run(procs, respawn, rpc_ports) -> None:
    def meshed():
        try:
            return all(
                rpc(p, "server_info")["info"]["peers"] == N - 1
                for p in rpc_ports
            )
        except Exception:
            return False

    t0 = time.monotonic()
    while not meshed():
        if time.monotonic() - t0 > 120:
            raise SystemExit("net never meshed")
        time.sleep(2)
    print(f"meshed in {time.monotonic()-t0:.0f}s", flush=True)

    master = KeyPair.from_passphrase("masterpassphrase")
    stop = threading.Event()
    stats = {"submitted": 0, "errors": 0, "kills": 0}

    def load():
        i = 0
        while not stop.is_set():
            try:
                rpc(rpc_ports[i % N], "submit", {
                    "secret": "masterpassphrase",
                    "tx_json": {
                        "TransactionType": "Payment",
                        "Account": master.human_account_id,
                        "Destination": KeyPair.from_passphrase(
                            f"chaos-dst-{i % 5}"
                        ).human_account_id,
                        "Amount": str(1_500_000_000),
                    },
                }, timeout=15)
                stats["submitted"] += 1
            except Exception:
                stats["errors"] += 1
            i += 1
            stop.wait(1.0)

    t = threading.Thread(target=load, daemon=True)
    t.start()
    rng = random.Random(7)
    deadline = time.monotonic() + MINUTES * 60
    try:
        while time.monotonic() < deadline:
            time.sleep(45)
            victim = rng.randrange(N)
            procs[victim].terminate()
            try:
                procs[victim].wait(timeout=10)
            except subprocess.TimeoutExpired:
                procs[victim].kill()
            stats["kills"] += 1
            time.sleep(4)
            respawn(victim)
            print(f"t+{time.monotonic()-t0:.0f}s killed/revived v{victim} "
                  f"(submitted={stats['submitted']})", flush=True)
    finally:
        stop.set()
        t.join(timeout=10)

    def seqs():
        out = []
        for p in rpc_ports:
            try:
                out.append(
                    rpc(p, "server_info")["info"]["validated_ledger"]["seq"]
                )
            except Exception:
                out.append(-1)
        return out

    target = max(seqs()) + 2
    t1 = time.monotonic()
    last = seqs()
    while min(last) < target:
        if time.monotonic() - t1 > 180:
            raise SystemExit(f"no convergence: {last}")
        time.sleep(3)
        last = seqs()
    # use the LAST in-loop observation — a fresh RPC round-trip here can
    # transiently fail and would poison `common` with a -1
    common = min(last)
    hashes = {
        rpc(p, "ledger", {"ledger_index": common})["ledger"]["hash"]
        for p in rpc_ports
    }
    ok = len(hashes) == 1
    print(json.dumps({
        "chaos_minutes": MINUTES, "kills": stats["kills"],
        "submitted": stats["submitted"], "errors": stats["errors"],
        "final_validated_seqs": last, "single_hash": ok,
        "summary": True,
    }), flush=True)
    if not ok:
        raise SystemExit(f"FORK at {common}: {hashes}")


if __name__ == "__main__":
    main()
