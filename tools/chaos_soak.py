"""Randomized chaos soak over a REAL 4-validator TCP+TLS net.

The standalone, longer-running sibling of
tests/test_multiproc_net.py::test_load_restart_convergence (the r4
build-time soak that surfaced the fork-repair fixes): continuous RPC
payment load while a validator is killed and revived every ~45s
(rotating victims), for `minutes` (default 12). Ends by asserting every
validator is quorum-validated on one advancing chain with one hash, and
prints a JSON summary line.

Usage: python tools/chaos_soak.py [minutes] [> CHAOS_SOAK.log]
"""

from __future__ import annotations

import json
import os
import random
import socket
import subprocess
import sys
import threading
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from stellard_tpu.protocol.keys import KeyPair  # noqa: E402

MINUTES = float(sys.argv[1]) if len(sys.argv) > 1 else 12.0
N = 4
SPEED = 5.0


def free_ports(k):
    socks = [socket.socket() for _ in range(k)]
    for s in socks:
        s.bind(("127.0.0.1", 0))
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


def rpc(port, method, params=None, timeout=10):
    req = json.dumps({"method": method, "params": [params or {}]}).encode()
    r = urllib.request.urlopen(
        f"http://127.0.0.1:{port}/", req, timeout=timeout
    )
    return json.loads(r.read())["result"]


def main() -> None:
    import tempfile

    tmp = tempfile.mkdtemp(prefix="chaos-")
    ports = free_ports(2 * N)
    peer_ports, rpc_ports = ports[:N], ports[N:]
    keys = [KeyPair.from_passphrase(f"chaos-val-{i}") for i in range(N)]
    for i in range(N):
        others_keys = "\n".join(
            keys[j].human_node_public for j in range(N) if j != i
        )
        others_addrs = "\n".join(
            f"127.0.0.1 {peer_ports[j]}" for j in range(N) if j != i
        )
        cfg = (
            f"[standalone]\n0\n\n[node_db]\ntype=memory\n\n"
            f"[signature_backend]\ntype=cpu\n\n"
            f"[validation_seed]\n{keys[i].human_seed}\n\n"
            f"[validators]\n{others_keys}\n\n[validation_quorum]\n3\n\n"
            f"[peer_port]\n{peer_ports[i]}\n\n[peer_ssl]\nrequire\n\n"
            f"[ips]\n{others_addrs}\n\n[clock_speed]\n{SPEED}\n\n"
            f"[rpc_port]\n{rpc_ports[i]}\n"
        )
        open(os.path.join(tmp, f"v{i}.cfg"), "w").write(cfg)

    procs: list = [None] * N

    def respawn(i):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        procs[i] = subprocess.Popen(
            [sys.executable, "-m", "stellard_tpu", "--conf",
             os.path.join(tmp, f"v{i}.cfg"), "--start"],
            cwd=REPO, env=env,
            stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT,
        )

    for i in range(N):
        respawn(i)

    def meshed():
        try:
            return all(
                rpc(p, "server_info")["info"]["peers"] == N - 1
                for p in rpc_ports
            )
        except Exception:
            return False

    t0 = time.monotonic()
    while not meshed():
        if time.monotonic() - t0 > 120:
            raise SystemExit("net never meshed")
        time.sleep(2)
    print(f"meshed in {time.monotonic()-t0:.0f}s", flush=True)

    master = KeyPair.from_passphrase("masterpassphrase")
    stop = threading.Event()
    stats = {"submitted": 0, "errors": 0, "kills": 0}

    def load():
        i = 0
        while not stop.is_set():
            try:
                rpc(rpc_ports[i % N], "submit", {
                    "secret": "masterpassphrase",
                    "tx_json": {
                        "TransactionType": "Payment",
                        "Account": master.human_account_id,
                        "Destination": KeyPair.from_passphrase(
                            f"chaos-dst-{i % 5}"
                        ).human_account_id,
                        "Amount": str(1_500_000_000),
                    },
                }, timeout=15)
                stats["submitted"] += 1
            except Exception:
                stats["errors"] += 1
            i += 1
            stop.wait(1.0)

    t = threading.Thread(target=load, daemon=True)
    t.start()
    rng = random.Random(7)
    deadline = time.monotonic() + MINUTES * 60
    try:
        while time.monotonic() < deadline:
            time.sleep(45)
            victim = rng.randrange(N)
            procs[victim].terminate()
            try:
                procs[victim].wait(timeout=10)
            except subprocess.TimeoutExpired:
                procs[victim].kill()
            stats["kills"] += 1
            time.sleep(4)
            respawn(victim)
            print(f"t+{time.monotonic()-t0:.0f}s killed/revived v{victim} "
                  f"(submitted={stats['submitted']})", flush=True)
    finally:
        stop.set()
        t.join(timeout=10)

    def seqs():
        out = []
        for p in rpc_ports:
            try:
                out.append(
                    rpc(p, "server_info")["info"]["validated_ledger"]["seq"]
                )
            except Exception:
                out.append(-1)
        return out

    target = max(seqs()) + 2
    t1 = time.monotonic()
    while min(seqs()) < target:
        if time.monotonic() - t1 > 180:
            raise SystemExit(f"no convergence: {seqs()}")
        time.sleep(3)
    common = min(seqs())
    hashes = {
        rpc(p, "ledger", {"ledger_index": common})["ledger"]["hash"]
        for p in rpc_ports
    }
    ok = len(hashes) == 1
    for p in procs:
        p.terminate()
    print(json.dumps({
        "chaos_minutes": MINUTES, "kills": stats["kills"],
        "submitted": stats["submitted"], "errors": stats["errors"],
        "final_validated_seqs": seqs(), "single_hash": ok,
        "summary": True,
    }), flush=True)
    if not ok:
        raise SystemExit(f"FORK at {common}: {hashes}")


if __name__ == "__main__":
    main()
