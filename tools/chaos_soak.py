"""Randomized chaos soak over a REAL 4-validator TCP+TLS net.

Now a thin wrapper over the scenario plane: the SAME `chaos` scenario
definition (stellard_tpu/testkit/scenarios.py — rotating validator
kills under continuous flood) that tools/scenariosmoke.py replays
deterministically on the simnet runs here against real processes via
testkit.tcpnet.run_tcp. Ends by asserting every validator is
quorum-validated on one advancing chain with one hash, and prints a
JSON scorecard line. Validators are always torn down, even on a failed
run.

Usage: python tools/chaos_soak.py [minutes] [seed] [> CHAOS_SOAK.log]
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from stellard_tpu.testkit.scenarios import scenario_chaos  # noqa: E402
from stellard_tpu.testkit.tcpnet import run_tcp  # noqa: E402

MINUTES = float(sys.argv[1]) if len(sys.argv) > 1 else 12.0
SEED = int(sys.argv[2]) if len(sys.argv) > 2 else 7


def main() -> None:
    steps = max(60, int(MINUTES * 60))  # 1 step ~= 1 second
    scn = scenario_chaos(seed=SEED, steps=steps, kill_every=45,
                         downtime=5)
    card = run_tcp(scn)
    card["chaos_minutes"] = MINUTES
    card["summary"] = True
    print(json.dumps(card), flush=True)
    if not card["converged"]:
        raise SystemExit(f"no convergence: {card['validated_seqs']}")
    if not card["single_hash"]:
        raise SystemExit(f"FORK at {card['final_seq']}")


if __name__ == "__main__":
    main()
