"""DEPRECATION SHIM — the chaos soak now lives in
``tools/scenariofuzz.py --soak`` (the scenario-search CLI owns every
harness over the scenario plane). Existing invocations keep working:

Usage: python tools/chaos_soak.py [minutes] [seed] [> CHAOS_SOAK.log]
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools.scenariofuzz import soak  # noqa: E402

MINUTES = float(sys.argv[1]) if len(sys.argv) > 1 else 12.0
SEED = int(sys.argv[2]) if len(sys.argv) > 2 else 7


def main() -> None:
    print(
        "chaos_soak.py is deprecated; use "
        "`python tools/scenariofuzz.py --soak [minutes] [seed]`",
        file=sys.stderr,
    )
    soak(MINUTES, SEED)


if __name__ == "__main__":
    main()
