"""Long-soak the cpplog NodeStore: growth, reopen time, read latency.

The LevelDB-role blind spot from SURVEY §2.8 / VERDICT r4 #9: cpplog
stores raw (uncompressed) content-addressed blobs in an append-only log;
nobody had measured store growth or reopen cost over a long run. This
soak writes ledger-shaped batches (SHAMap-node-sized blobs, hash-keyed)
at a paced rate, and periodically:

  - records logical bytes written vs file size on disk (overhead ratio),
  - closes + reopens the store, timing the reopen (index rebuild scan),
  - reads a random sample of historical keys, timing fetch latency.

Paced (default one batch per 2s) so it can run for hours beside the
build without owning the box. Appends one JSON line per checkpoint to
the output file; the final line carries `"summary": true`.

Usage: python tools/cpplog_soak.py [minutes] [out.jsonl]
"""

from __future__ import annotations

import hashlib
import json
import os
import random
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from stellard_tpu.nodestore.core import NodeObject, NodeObjectType, make_backend  # noqa: E402

MINUTES = float(sys.argv[1]) if len(sys.argv) > 1 else 120.0
OUT = sys.argv[2] if len(sys.argv) > 2 else os.path.join(REPO, "SOAK_CPPLOG.jsonl")
STORE = os.environ.get("SOAK_STORE", "/tmp/stellard_soak.cpplog")
BATCH = int(os.environ.get("SOAK_BATCH", "400"))  # ~1 closed ledger's nodes
PACE_S = float(os.environ.get("SOAK_PACE_S", "2.0"))
CHECKPOINT_EVERY = int(os.environ.get("SOAK_CHECKPOINT", "120"))  # batches


def _mk_batch(rng: random.Random, seq: int) -> list[NodeObject]:
    """SHAMap-shaped blobs: mostly inner nodes (~512B of child hashes)
    and account-state leaves (~200B), a few tx+meta items (~600B)."""
    out = []
    for i in range(BATCH):
        kind = rng.random()
        if kind < 0.55:
            size, t = 512, NodeObjectType.ACCOUNT_NODE
        elif kind < 0.9:
            size, t = 200, NodeObjectType.ACCOUNT_NODE
        else:
            size, t = 600, NodeObjectType.TRANSACTION_NODE
        data = rng.randbytes(size - 8) + seq.to_bytes(4, "big") + i.to_bytes(4, "big")
        out.append(NodeObject(t, hashlib.sha256(data).digest(), data))
    return out


def main() -> None:
    rng = random.Random(42)
    if os.path.exists(STORE):
        os.remove(STORE)
    be = make_backend("cpplog", path=STORE)
    deadline = time.monotonic() + MINUTES * 60
    written = 0
    logical = 0
    keys: list[bytes] = []
    t_start = time.monotonic()
    batches = 0
    write_s = 0.0
    f = open(OUT, "a")

    def checkpoint(reopen: bool) -> dict:
        nonlocal be
        size = os.path.getsize(STORE)
        row = {
            "t_min": round((time.monotonic() - t_start) / 60, 2),
            "batches": batches,
            "objects": written,
            "logical_mb": round(logical / 1e6, 2),
            "file_mb": round(size / 1e6, 2),
            "overhead": round(size / logical, 4) if logical else 0.0,
            "write_mb_s": round(logical / 1e6 / write_s, 2) if write_s else 0.0,
        }
        sample = rng.sample(keys, min(200, len(keys)))
        t0 = time.perf_counter()
        misses = sum(1 for k in sample if be.fetch(k) is None)
        row["fetch_us"] = round(
            (time.perf_counter() - t0) / max(1, len(sample)) * 1e6, 1)
        row["fetch_misses"] = misses
        if reopen:
            be.close()
            t0 = time.perf_counter()
            be = make_backend("cpplog", path=STORE)
            row["reopen_s"] = round(time.perf_counter() - t0, 3)
            # reopened store must still serve a historical key
            k = rng.choice(keys)
            row["reopen_fetch_ok"] = be.fetch(k) is not None
        f.write(json.dumps(row) + "\n")
        f.flush()
        return row

    while time.monotonic() < deadline:
        batch = _mk_batch(rng, batches)
        t0 = time.perf_counter()
        be.store_batch(batch)
        write_s += time.perf_counter() - t0
        batches += 1
        written += len(batch)
        logical += sum(len(o.data) for o in batch)
        if len(keys) < 50_000:
            keys.extend(o.hash for o in batch[:20])
        if batches % CHECKPOINT_EVERY == 0:
            checkpoint(reopen=(batches % (CHECKPOINT_EVERY * 4) == 0))
        time.sleep(PACE_S)

    row = checkpoint(reopen=True)
    row["summary"] = True
    f.write(json.dumps(row) + "\n")
    f.close()
    be.close()
    print(json.dumps(row))


if __name__ == "__main__":
    main()
