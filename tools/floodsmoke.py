"""Tier-1 flood smoke: the overlay defense plane as a regression gate.

Runs the flood_survival scenario — a 5-validator core plus a relay-peer
tier (200 nodes at the smoke's size), validator-message squelching,
enforced resource pricing, and one hostile relay peer flooding garbage
frames, same-source duplicates, and junk txs at its whole neighbor
set — twice with one seed, asserting:

- convergence: every honest validator quorum-validated on ONE identical
  chain despite the flood, with the full workload committed;
- enforcement: the flooder's endpoint reaches DROP at its flooded
  neighbors and its deliveries are then REFUSED (disconnect + gated
  readmission), pinned by `resource.*` counters — dropped > 0,
  refused > 0, and every flooded neighbor refusing;
- squelch bound: per-node relay fan-out for proposals/validations never
  exceeds squelch_size + |UNL| — bounded by the subset, NOT the peer
  count (the anti-vacuity side: relays actually happened);
- degradation budget: honest close cadence (validated seq reached in
  the same step budget) within 25% of the SAME seed with no flooder;
- determinism: two runs of one seed produce byte-identical scorecards.

Usage: python tools/floodsmoke.py [seed]
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from stellard_tpu.testkit.scenario import run_simnet  # noqa: E402
from stellard_tpu.testkit.scenarios import scenario_flood_survival  # noqa: E402

SEED = int(sys.argv[1]) if len(sys.argv) > 1 else 7
N_PEERS = int(os.environ.get("FLOODSMOKE_PEERS", "195"))  # 200 nodes
STEPS = 44


def fail(msg: str) -> None:
    print(f"FLOOD SMOKE FAILED: {msg}", file=sys.stderr)
    raise SystemExit(2)


def main() -> None:
    scn = scenario_flood_survival(seed=SEED, n_peers=N_PEERS, steps=STEPS)
    a = run_simnet(scn)
    b = run_simnet(
        scenario_flood_survival(seed=SEED, n_peers=N_PEERS, steps=STEPS)
    )
    print(json.dumps(a), flush=True)

    # determinism across runs (cross-process determinism of the same
    # scorecard is pinned by tests/test_overlay_defense.py)
    if json.dumps(a, sort_keys=True) != json.dumps(b, sort_keys=True):
        for k in sorted(set(a) | set(b)):
            if a.get(k) != b.get(k):
                print(f"  diverged field {k!r}", file=sys.stderr)
        fail(f"scorecard not deterministic for seed {SEED}")

    # convergence under fire
    if not a["converged"]:
        fail(f"honest validators never converged ({a['validated_seqs']})")
    if not a["single_hash"]:
        fail(f"FORK at seq {a['final_seq']}")
    if a["committed"] < a["submitted"]:
        fail(f"workload lost under flood: {a['committed']}/{a['submitted']}")

    # enforcement: the flooder reached DROP and was refused readmission
    res = a["resource"]
    if res["dropped"] <= 0 or res["refused"] <= 0:
        fail(f"flooder never crossed the DROP line: {res}")
    fl = next(iter(a["flooders"].values()))
    fan = scn.flooders[0]["fan"]
    if fl["refused_by"] < fan:
        fail(
            f"only {fl['refused_by']}/{fan} flooded neighbors refused "
            f"the flooder"
        )
    # anti-vacuity: the flood actually happened
    if min(fl["emitted"].values()) <= 0:
        fail(f"flooder emitted nothing: {fl['emitted']}")

    # squelch bound: fan-out limited by the subset + UNL, never by the
    # peer count — and relays actually flowed through the subsets
    bound = scn.squelch_size + scn.n_validators
    relay = a["relay"]
    if relay["relay_fanout_max"] > bound:
        fail(
            f"relay fan-out {relay['relay_fanout_max']} exceeds the "
            f"squelch bound {bound}"
        )
    if relay["relay_proposal"] <= 0 or relay["relay_validation"] <= 0:
        fail(f"no squelched relays recorded: {relay}")

    # degradation budget vs the SAME seed with no flooder: the virtual
    # close cadence (validated seq reached inside the fixed step
    # budget) must hold within 25%
    base = run_simnet(scenario_flood_survival(
        seed=SEED, n_peers=N_PEERS, steps=STEPS, flooder=False,
    ))
    if not base["converged"] or not base["single_hash"]:
        fail("no-flooder baseline did not converge (harness bug)")
    if a["final_seq"] < 0.75 * base["final_seq"]:
        fail(
            f"close cadence degraded >25% under flood: seq "
            f"{a['final_seq']} vs baseline {base['final_seq']}"
        )

    print(json.dumps({
        "floodsmoke": "ok",
        "seed": SEED,
        "nodes": scn.n_validators + scn.n_peers,
        "final_seq": a["final_seq"],
        "baseline_seq": base["final_seq"],
        "relay_fanout_max": relay["relay_fanout_max"],
        "squelch_bound": bound,
        "flooder_refused_by": fl["refused_by"],
        "resource": {k: res[k] for k in (
            "charged", "warned", "dropped", "refused", "throttled",
        )},
    }), flush=True)


if __name__ == "__main__":
    main()
