"""Tier-1 follower-TREE smoke: the cascading read tier as a gate.

Boots a LEADER (networked solo validator, quorum=1), a mid-tier
follower F1 dialing the leader, and a leaf follower F2 whose
``[node] upstream=`` names F1 — a depth-2 cascade over real TCP — then
floods the leader and asserts the whole tree contract end-to-end:

- ingest identity at EVERY tier: F1's and F2's ledger hash at every
  validated seq is byte-identical to the leader's (the ledger hash
  covers the state and tx tree roots, so this is state-root identity);
- O(children) leader egress: F2 lists the leader in [ips] but its
  upstream= override dials F1 instead, so the leader's peer table
  holds exactly ONE session (F1) while both followers sync — the
  leader's fan-out is bounded by its direct children, not the tier;
- cascade serving: F2 acquires ledgers/segments FROM F1 (its only
  session), i.e. a follower re-publishes the validated chain
  downstream;
- cold catch-up through the tree: both followers boot AFTER the
  leader has closed ledgers and must join the validated chain;
- serving mid-flood: read RPCs answered from F1's real HTTP door
  WHILE the leader floods, with the validated-seq result cache
  taking hits;
- resume cursors (reconnect-storm hardening): a subscriber on F2 is
  dropped mid-stream and a reconnecting client presents its
  last-delivered seq — the replay ring fills the gap with ZERO missed
  seqs, and a cursor past the horizon gets the explicit cold answer;
- no rounds: neither follower ever runs consensus.

Runtime: ~45-90s (clock_speed-accelerated consensus).

Usage: python tools/followersmoke.py
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SPEED = 5.0


def fail(msg: str) -> None:
    print(f"FOLLOWER SMOKE FAILED: {msg}", file=sys.stderr)
    raise SystemExit(2)


def main() -> None:
    import tempfile

    import jax

    jax.config.update("jax_platforms", "cpu")

    from stellard_tpu.node.config import Config
    from stellard_tpu.node.node import Node
    from stellard_tpu.protocol.formats import TxType
    from stellard_tpu.protocol.keys import KeyPair
    from stellard_tpu.protocol.sfields import sfAmount, sfDestination
    from stellard_tpu.protocol.stamount import STAmount
    from stellard_tpu.protocol.sttx import SerializedTransaction
    from stellard_tpu.rpc.infosub import InfoSub
    from stellard_tpu.testkit.tcpnet import free_ports, rpc, wait_until

    tmp = tempfile.mkdtemp(prefix="followersmoke-")
    leader_peer, f1_peer, f2_peer = free_ports(3)
    val_key = KeyPair.from_passphrase("followersmoke-leader")

    leader = Node(Config(
        standalone=False,
        signature_backend="cpu",
        node_db_type="segstore",
        node_db_path=os.path.join(tmp, "leader-ns"),
        database_path=os.path.join(tmp, "leader.db"),
        validation_seed=val_key.human_seed,
        validation_quorum=1,
        peer_port=leader_peer,
        clock_speed=SPEED,
        rpc_port=0,
    )).setup().serve()

    f1 = f2 = None
    try:
        # phase 1: leader alone closes a few ledgers so the followers
        # later boot COLD and must catch up
        master = leader.master_keys

        def payment(seq: int, dest: bytes) -> SerializedTransaction:
            tx = SerializedTransaction.build(
                TxType.ttPAYMENT, master.account_id, seq, 10,
                {sfAmount: STAmount.from_drops(250_000_000),
                 sfDestination: dest},
            )
            tx.sign(master)
            return tx

        dests = [KeyPair.from_passphrase(f"fsmoke-{i}").account_id
                 for i in range(8)]
        acked = threading.Semaphore(0)

        def cb(_tx, _ter, _applied):
            acked.release()

        next_seq = 1
        for _ in range(30):
            leader.ops.submit_transaction(
                payment(next_seq, dests[next_seq % len(dests)]), cb)
            next_seq += 1
        for _ in range(30):
            acked.acquire()

        def leader_validated():
            v = leader.ledger_master.validated
            return v.seq if v is not None else 0

        if not wait_until(lambda: leader_validated() >= 3, 90, 0.5):
            fail(f"leader never validated 3 ledgers solo "
                 f"(validated={leader_validated()})")

        def follower_cfg(name: str, port: int, dial: list[str],
                         upstream: list[str]) -> Config:
            return Config(
                standalone=False,
                node_mode="follower",
                signature_backend="cpu",
                node_db_type="segstore",
                node_db_path=os.path.join(tmp, f"{name}-ns"),
                database_path=os.path.join(tmp, f"{name}.db"),
                validators=[val_key.human_node_public],
                validation_quorum=1,
                peer_port=port,
                ips=dial,
                node_upstream=upstream,
                clock_speed=SPEED,
                rpc_port=0,
            )

        # phase 2: boot the mid-tier follower F1 (upstream= names the
        # leader: tier-1 followers ARE the leader's direct children),
        # then the leaf F2 — F2 lists the LEADER in [ips] but its
        # upstream= override must dial F1 instead (the config contract
        # the tree topology rides on)
        f1 = Node(follower_cfg(
            "f1", f1_peer, [], [f"127.0.0.1 {leader_peer}"],
        )).setup().serve()
        f1port = f1.http_server.port

        def validated_of(node):
            v = node.ledger_master.validated
            return v.seq if v is not None else 0

        if not wait_until(
            lambda: validated_of(f1) >= leader_validated() - 1
            and validated_of(f1) >= 3, 120, 0.5,
        ):
            fail(f"F1 never caught up (f1={validated_of(f1)}, "
                 f"leader={leader_validated()})")

        f2 = Node(follower_cfg(
            "f2", f2_peer, [f"127.0.0.1 {leader_peer}"],
            [f"127.0.0.1 {f1_peer}"],
        )).setup().serve()

        # resume-cursor leg, part 1: a ledger-stream subscriber on the
        # LEAF follower accumulates events it will later resume past
        events_a: list[dict] = []
        sub_a = InfoSub(events_a.append)
        f2.subs.subscribe_streams(sub_a, ["ledger"])

        if not wait_until(
            lambda: validated_of(f2) >= leader_validated() - 1
            and validated_of(f2) >= 3, 120, 0.5,
        ):
            fail(f"F2 never caught up through F1 (f2={validated_of(f2)}, "
                 f"f1={validated_of(f1)}, leader={leader_validated()})")

        # gate 1: O(children) leader egress — the leader holds exactly
        # ONE peer session (F1); F2's upstream= kept it off the leader
        leader_peers = len(leader.overlay.peers)
        if leader_peers != 1:
            fail(f"leader egress not bounded by direct children: "
                 f"{leader_peers} peer sessions (want 1 — F1 only)")
        if len(f2.overlay.peers) != 1:
            fail(f"F2 should hold exactly its upstream session, has "
                 f"{len(f2.overlay.peers)}")

        # phase 3: flood the leader WHILE reading from F1
        reads = {"ok": 0, "err": 0}
        stop_flood = threading.Event()

        def flood():
            nonlocal next_seq
            while not stop_flood.is_set():
                for _ in range(10):
                    leader.ops.submit_transaction(
                        payment(next_seq, dests[next_seq % len(dests)]),
                        cb,
                    )
                    next_seq += 1
                time.sleep(0.05)

        flooder = threading.Thread(target=flood, daemon=True)
        flooder.start()
        t_end = time.monotonic() + 12.0
        master_id = master.human_account_id
        while time.monotonic() < t_end:
            try:
                r = rpc(f1port, "account_info", {"account": master_id})
                if r.get("status") == "success" and "account_data" in r:
                    reads["ok"] += 1
                else:
                    reads["err"] += 1
                r = rpc(f1port, "ledger", {"ledger_index": "validated"})
                if r.get("status") != "success":
                    reads["err"] += 1
            except Exception:
                reads["err"] += 1
            time.sleep(0.02)
        stop_flood.set()
        flooder.join(timeout=5)

        if reads["ok"] < 20:
            fail(f"F1 served too few reads mid-flood: {reads}")
        if reads["err"] > reads["ok"] // 10:
            fail(f"F1 read errors mid-flood: {reads}")

        # resume-cursor leg, part 2: the client "drops" (unregisters)
        # holding a cursor, misses a few closes, then reconnects and
        # resumes — the ring must replay the gap with zero missed seqs
        f2.subs.flush(timeout=10.0)
        a_seqs = [e["ledger_index"] for e in events_a
                  if e.get("type") == "ledgerClosed"]
        if len(a_seqs) < 3:
            fail(f"too few ledgerClosed events before the drop: {a_seqs}")
        cursor = max(a_seqs)
        f2.subs.remove(sub_a.id)

        if not wait_until(
            lambda: validated_of(f2) >= cursor + 2, 120, 0.5,
        ):
            fail(f"F2 never advanced past the dropped cursor "
                 f"(cursor={cursor}, f2={validated_of(f2)})")

        events_b: list[dict] = []
        sub_b = InfoSub(events_b.append)
        res = f2.subs.resume(sub_b, cursor)
        if not res.get("resumed") or res.get("cold"):
            fail(f"resume from live cursor {cursor} answered cold: {res}")
        if res.get("replayed", 0) < 1:
            fail(f"resume replayed nothing past cursor {cursor}: {res}")
        f2.subs.flush(timeout=10.0)
        b_seqs = [e["ledger_index"] for e in events_b
                  if e.get("type") == "ledgerClosed"]
        if not b_seqs:
            fail("resumed subscriber received no events")
        if b_seqs != sorted(set(b_seqs)):
            fail(f"resumed stream out of order or duplicated: {b_seqs}")
        combined = sorted(set(a_seqs) | set(b_seqs))
        expect = list(range(combined[0], combined[-1] + 1))
        if combined != expect:
            fail(f"resume left a gap: delivered {combined}, "
                 f"want contiguous {expect[0]}..{expect[-1]}")
        if min(b_seqs) != cursor + 1:
            fail(f"resume did not restart at cursor+1: first replayed "
                 f"{min(b_seqs)}, cursor {cursor}")

        # a cursor past the horizon must get the EXPLICIT cold answer
        # (never a silent gap): seq 0 predates any ring entry
        probe = f2.subs.resume(InfoSub(lambda m: None), 0)
        if not probe.get("cold"):
            fail(f"past-horizon resume not answered cold: {probe}")

        # let the tail drain: both tiers converge on the leader's tip
        target = leader_validated()
        if not wait_until(
            lambda: validated_of(f1) >= target
            and validated_of(f2) >= target, 120, 0.5,
        ):
            fail(f"tree stalled (f1={validated_of(f1)}, "
                 f"f2={validated_of(f2)}, leader={target})")

        # gate 2: state-root byte identity at EVERY validated seq,
        # at EVERY tier
        common = min(leader_validated(), validated_of(f1),
                     validated_of(f2))
        lh = leader.ledger_master.ledger_history
        checked = 0
        for seq in range(2, common + 1):
            a = lh.get(seq)
            b1 = f1.ledger_master.ledger_history.get(seq)
            b2 = f2.ledger_master.ledger_history.get(seq)
            if a is None:
                continue  # aged out of the bounded index
            for tier, b in (("f1", b1), ("f2", b2)):
                if b is not None and a != b:
                    fail(f"ledger hash mismatch at {tier} seq {seq}: "
                         f"{a.hex()} != {b.hex()}")
            if b1 is not None and b2 is not None:
                checked += 1
        if checked < 3:
            fail(f"too few comparable seqs ({checked})")

        # gate 3: neither follower ever ran consensus, both actually
        # ingested (anti-vacuity)
        for name, f in (("f1", f1), ("f2", f2)):
            vn = f.overlay.node
            if vn.rounds_completed != 0:
                fail(f"{name} completed {vn.rounds_completed} consensus "
                     f"rounds — followers must never close")
            if vn.ledgers_ingested < 3:
                fail(f"{name} ingested only {vn.ledgers_ingested} ledgers")

        # gate 4: the result cache took hits on the serving tier
        for _ in range(5):
            rpc(f1port, "account_info", {"account": master_id})
        cj = f1.read_cache.get_json()
        if cj["hits"] <= 0:
            fail(f"validated-seq result cache never hit: {cj}")
        if f1.read_plane.snapshot() is None:
            fail("F1 read plane never published a snapshot")

        vn1 = f1.overlay.node
        vn2 = f2.overlay.node
        sj = f2.subs.get_json()
        print(json.dumps({
            "follower_smoke": "ok",
            "validated_seq": common,
            "seqs_hash_checked": checked,
            "leader_peer_sessions": leader_peers,
            "ledgers_ingested": {
                "f1": vn1.ledgers_ingested, "f2": vn2.ledgers_ingested,
            },
            "lcl_kicks": {
                "inline": vn2.lcl_inline_kicks,
                "coalesced": vn2.lcl_kicks_coalesced,
            },
            "reads_mid_flood": reads,
            "cache": {k: cj[k] for k in ("hits", "misses", "hit_rate")},
            "resume": res,
            "resume_counters": {
                k: sj[k] for k in ("resumed", "resume_replayed",
                                   "resume_cold", "dup_suppressed")
            },
            "segfetch": {
                name: (vn.segment_catchup.get_json()
                       if vn.segment_catchup is not None else {})
                for name, vn in (("f1", vn1), ("f2", vn2))
            },
        }), flush=True)
    finally:
        if f2 is not None:
            f2.stop()
        if f1 is not None:
            f1.stop()
        leader.stop()
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    main()
