"""Tier-1 follower smoke: the read-plane scale-out tier as a gate.

Boots a LEADER (networked solo validator, quorum=1) and a FOLLOWER
([node] mode=follower) over a real TCP peer link, floods the leader,
and asserts the whole follower contract end-to-end:

- ingest identity: the follower's ledger hash at EVERY validated seq is
  byte-identical to the leader's (the ledger hash covers the state and
  tx tree roots, so this is state-root identity);
- cold catch-up: the follower boots AFTER the leader has closed
  ledgers and must join the validated chain (bulk segment path armed);
- serving mid-flood: read RPCs answered from the follower's real HTTP
  door WHILE the leader floods, resolved against the validated
  snapshot, with the validated-seq result cache taking hits;
- subscription order: ledgerClosed events delivered through the
  sharded fanout arrive in strictly increasing seq order, and per-tx
  events ride along;
- no rounds: the follower never runs consensus (rounds_completed == 0).

Runtime: ~30-60s (clock_speed-accelerated consensus).

Usage: python tools/followersmoke.py
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SPEED = 5.0


def fail(msg: str) -> None:
    print(f"FOLLOWER SMOKE FAILED: {msg}", file=sys.stderr)
    raise SystemExit(2)


def main() -> None:
    import tempfile

    import jax

    jax.config.update("jax_platforms", "cpu")

    from stellard_tpu.node.config import Config
    from stellard_tpu.node.node import Node
    from stellard_tpu.protocol.formats import TxType
    from stellard_tpu.protocol.keys import KeyPair
    from stellard_tpu.protocol.sfields import sfAmount, sfDestination
    from stellard_tpu.protocol.stamount import STAmount
    from stellard_tpu.protocol.sttx import SerializedTransaction
    from stellard_tpu.rpc.infosub import InfoSub
    from stellard_tpu.testkit.tcpnet import free_ports, rpc, wait_until

    tmp = tempfile.mkdtemp(prefix="followersmoke-")
    leader_peer, follower_peer = free_ports(2)
    val_key = KeyPair.from_passphrase("followersmoke-leader")

    leader = Node(Config(
        standalone=False,
        signature_backend="cpu",
        node_db_type="segstore",
        node_db_path=os.path.join(tmp, "leader-ns"),
        database_path=os.path.join(tmp, "leader.db"),
        validation_seed=val_key.human_seed,
        validation_quorum=1,
        peer_port=leader_peer,
        clock_speed=SPEED,
        rpc_port=0,
    )).setup().serve()

    follower = None
    try:
        # phase 1: leader alone closes a few ledgers so the follower
        # later boots COLD and must catch up
        master = leader.master_keys

        def payment(seq: int, dest: bytes) -> SerializedTransaction:
            tx = SerializedTransaction.build(
                TxType.ttPAYMENT, master.account_id, seq, 10,
                {sfAmount: STAmount.from_drops(250_000_000),
                 sfDestination: dest},
            )
            tx.sign(master)
            return tx

        dests = [KeyPair.from_passphrase(f"fsmoke-{i}").account_id
                 for i in range(8)]
        acked = threading.Semaphore(0)

        def cb(_tx, _ter, _applied):
            acked.release()

        next_seq = 1
        for _ in range(30):
            leader.ops.submit_transaction(
                payment(next_seq, dests[next_seq % len(dests)]), cb)
            next_seq += 1
        for _ in range(30):
            acked.acquire()

        def leader_validated():
            v = leader.ledger_master.validated
            return v.seq if v is not None else 0

        if not wait_until(lambda: leader_validated() >= 3, 90, 0.5):
            fail(f"leader never validated 3 ledgers solo "
                 f"(validated={leader_validated()})")

        # phase 2: boot the follower cold
        follower = Node(Config(
            standalone=False,
            node_mode="follower",
            signature_backend="cpu",
            node_db_type="segstore",
            node_db_path=os.path.join(tmp, "follower-ns"),
            database_path=os.path.join(tmp, "follower.db"),
            validators=[val_key.human_node_public],
            validation_quorum=1,
            peer_port=follower_peer,
            ips=[f"127.0.0.1 {leader_peer}"],
            clock_speed=SPEED,
            rpc_port=0,
        )).setup().serve()
        fport = follower.http_server.port

        # subscription plane: ledger + account streams through the
        # sharded fanout (in-process sink; the WS door rides the same
        # manager and is covered by the RPC-server suite)
        events: list[dict] = []
        sub = InfoSub(events.append)
        follower.subs.subscribe_streams(sub, ["ledger", "transactions"])
        follower.subs.subscribe_accounts(sub, [dests[0]])

        def follower_validated():
            v = follower.ledger_master.validated
            return v.seq if v is not None else 0

        if not wait_until(
            lambda: follower_validated() >= leader_validated() - 1
            and follower_validated() >= 3, 120, 0.5,
        ):
            fail(f"follower never caught up (follower="
                 f"{follower_validated()}, leader={leader_validated()})")

        # phase 3: flood the leader WHILE reading from the follower
        reads = {"ok": 0, "err": 0}
        stop_flood = threading.Event()

        def flood():
            nonlocal next_seq
            while not stop_flood.is_set():
                for _ in range(10):
                    leader.ops.submit_transaction(
                        payment(next_seq, dests[next_seq % len(dests)]),
                        cb,
                    )
                    next_seq += 1
                time.sleep(0.05)

        flooder = threading.Thread(target=flood, daemon=True)
        flooder.start()
        t_end = time.monotonic() + 15.0
        master_id = master.human_account_id
        while time.monotonic() < t_end:
            try:
                r = rpc(fport, "account_info", {"account": master_id})
                if r.get("status") == "success" and "account_data" in r:
                    reads["ok"] += 1
                else:
                    reads["err"] += 1
                r = rpc(fport, "ledger", {"ledger_index": "validated"})
                if r.get("status") != "success":
                    reads["err"] += 1
            except Exception:
                reads["err"] += 1
            time.sleep(0.02)
        stop_flood.set()
        flooder.join(timeout=5)

        if reads["ok"] < 20:
            fail(f"follower served too few reads mid-flood: {reads}")
        if reads["err"] > reads["ok"] // 10:
            fail(f"follower read errors mid-flood: {reads}")

        # let the tail drain: follower converges on the leader's tip
        target = leader_validated()
        if not wait_until(lambda: follower_validated() >= target, 120, 0.5):
            fail(f"follower stalled at {follower_validated()} "
                 f"(leader={target})")

        # gate 1: state-root byte identity at EVERY validated seq
        common = min(leader_validated(), follower_validated())
        lh = leader.ledger_master.ledger_history
        fh = follower.ledger_master.ledger_history
        checked = 0
        for seq in range(2, common + 1):
            a, b = lh.get(seq), fh.get(seq)
            if a is None or b is None:
                continue  # aged out of the bounded index
            if a != b:
                fail(f"ledger hash mismatch at seq {seq}: "
                     f"{a.hex()} != {b.hex()}")
            checked += 1
        if checked < 3:
            fail(f"too few comparable seqs ({checked})")

        # gate 2: the follower never ran consensus, and actually
        # ingested (anti-vacuity)
        vn = follower.overlay.node
        if vn.rounds_completed != 0:
            fail(f"follower completed {vn.rounds_completed} consensus "
                 f"rounds — it must never close")
        if vn.ledgers_ingested < 3:
            fail(f"follower ingested only {vn.ledgers_ingested} ledgers")

        # gate 3: the result cache took hits (repeated identical read
        # against one validated seq) and reads resolved from the
        # validated snapshot
        for _ in range(5):
            rpc(fport, "account_info", {"account": master_id})
        cj = follower.read_cache.get_json()
        if cj["hits"] <= 0:
            fail(f"validated-seq result cache never hit: {cj}")
        if follower.read_plane.snapshot() is None:
            fail("follower read plane never published a snapshot")

        # gate 4: subscription events delivered IN ORDER through the
        # sharded fanout
        if not follower.subs.flush(timeout=10.0):
            fail("fanout shards never drained")
        closed_seqs = [e["ledger_index"] for e in events
                       if e.get("type") == "ledgerClosed"]
        if len(closed_seqs) < 3:
            fail(f"too few ledgerClosed events: {closed_seqs}")
        if closed_seqs != sorted(closed_seqs) or len(set(closed_seqs)) != len(
            closed_seqs
        ):
            fail(f"ledgerClosed events out of order: {closed_seqs}")
        if not any(e.get("type") == "transaction" for e in events):
            fail("no transaction events delivered")

        sj = follower.subs.get_json()
        print(json.dumps({
            "follower_smoke": "ok",
            "validated_seq": common,
            "seqs_hash_checked": checked,
            "ledgers_ingested": vn.ledgers_ingested,
            "reads_mid_flood": reads,
            "cache": {k: cj[k] for k in ("hits", "misses", "hit_rate")},
            "subs": {k: sj[k] for k in ("published", "delivered",
                                        "dropped_events")},
            "segfetch_started": (
                vn.segment_catchup.get_json()["started"]
                if vn.segment_catchup is not None else 0
            ),
            "ledger_closed_events": len(closed_seqs),
        }), flush=True)
    finally:
        if follower is not None:
            follower.stop()
        leader.stop()
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    main()
