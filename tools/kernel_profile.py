"""Decompose the on-chip cost of the batched Ed25519 verify kernel.

The full-kernel sweep (tools/kernel_sweep.py) answers "how fast"; this
answers "where does the time go" by timing isolated sub-kernels whose
field-op counts are known exactly:

  sq_chain    — N dependent fe_square on [20, B]   (the doubling/invert
                substrate: per-square cost, pure dependency chain)
  mul_chain   — N dependent fe_mul on [20, B]
  dbl_chain   — N dependent pt_double               (4S + 4M + adds)
  select_h    — 64 signed-digit one-hot table selects (the in-loop form)
  comb_mxu    — 64 one-hot [60,16]@[16,B] matmuls at HIGHEST precision
  encode      — pt_encode_words (fe_invert: 254 dependent squares + tail)

Each sub-kernel is wrapped in jit with a donated dummy carry so XLA
cannot elide the chain. Comparing (measured total) vs (sum of parts at
these rates) pins which formulation change pays: wider ops (grouped
muls), hoisted selects, shorter chains, or bigger batches.

Run on the TPU host: `python tools/kernel_profile.py [B ...]`.
Optionally set STELLARD_PROFILE_TRACE=/tmp/jaxtrace to also capture a
jax.profiler trace of one full verify_kernel invocation.
"""
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np

os.environ.pop("JAX_PLATFORMS", None)

import jax
import jax.numpy as jnp
from jax import lax

from stellard_tpu.utils.xlacache import enable_compilation_cache

enable_compilation_cache()

from stellard_tpu.ops import ed25519_jax as ej
from stellard_tpu.ops.fe25519 import NLIMB, fe_add, fe_mul, fe_square


def bench(fn, *args, reps=20, warmup=2):
    for _ in range(warmup):
        out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.time()
    for _ in range(reps):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.time() - t0) / reps


def rand_fe(B, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.integers(0, 8191, size=(NLIMB, B), dtype=np.int32))


def rand_pt(B, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(
        rng.integers(0, 8191, size=(4, NLIMB, B), dtype=np.int32)
    )


def main(batches):
    dev = jax.devices()[0]
    print(f"device: {dev.platform} {dev.device_kind}", flush=True)
    N = 64  # chain length for per-op timings

    @jax.jit
    def sq_chain(x):
        return lax.fori_loop(0, N, lambda i, a: fe_square(a), x)

    @jax.jit
    def mul_chain(x, y):
        return lax.fori_loop(0, N, lambda i, a: fe_mul(a, y), x)

    @jax.jit
    def add_chain(x, y):
        return lax.fori_loop(0, N, lambda i, a: fe_add(a, y), x)

    @jax.jit
    def dbl_chain(p):
        return lax.fori_loop(0, N, lambda i, a: ej.pt_double(a), p)

    @jax.jit
    def select_h(tbl, digits):
        def body(j, acc):
            d = lax.dynamic_index_in_dim(digits, j, axis=0, keepdims=False)
            return acc + ej._select_cached(tbl, d)

        return lax.fori_loop(0, N, body, jnp.zeros_like(tbl[0]))

    comb_np = ej._comb_table_np()

    @jax.jit
    def comb_mxu(comb, sw):
        def body(j, acc):
            tj = lax.dynamic_index_in_dim(comb, j, axis=0, keepdims=False)
            w = lax.dynamic_index_in_dim(sw, j, axis=0, keepdims=False)
            onehot = (
                w[None, :] == jnp.arange(16, dtype=w.dtype)[:, None]
            ).astype(jnp.float32)
            sel = (
                jnp.matmul(tj, onehot, precision=lax.Precision.HIGHEST)
                .astype(jnp.int32)
                .reshape((3, NLIMB) + w.shape)
            )
            return acc + sel

        z = jnp.zeros((3, NLIMB) + sw.shape[1:], jnp.int32)
        return lax.fori_loop(0, N, body, z)

    @jax.jit
    def comb_hoisted(comb, sw):
        onehot = (
            sw[:, None, :] == jnp.arange(16, dtype=sw.dtype)[None, :, None]
        ).astype(jnp.float32)  # [64, 16, B]
        sel = jnp.einsum(
            "jlw,jwb->jlb", comb, onehot, precision=lax.Precision.HIGHEST
        ).astype(jnp.int32)
        return sel.reshape((N, 3, NLIMB) + sw.shape[1:])

    @jax.jit
    def encode(p):
        return ej.pt_encode_words(p)

    for B in batches:
        rng = np.random.default_rng(1)
        x, y = rand_fe(B, 1), rand_fe(B, 2)
        p = rand_pt(B, 3)
        tbl = jnp.asarray(
            rng.integers(0, 8191, size=(9, 4, NLIMB, B), dtype=np.int32)
        )
        digits = jnp.asarray(
            rng.integers(-8, 8, size=(N, B), dtype=np.int32)
        )
        sw = jnp.asarray(rng.integers(0, 16, size=(N, B), dtype=np.int32))
        comb = jnp.asarray(comb_np)

        rows = [
            ("sq_chain", lambda: bench(sq_chain, x), N),
            ("mul_chain", lambda: bench(mul_chain, x, y), N),
            ("add_chain", lambda: bench(add_chain, x, y), N),
            ("dbl_chain", lambda: bench(dbl_chain, p), N),
            ("select_h", lambda: bench(select_h, tbl, digits), N),
            ("comb_mxu", lambda: bench(comb_mxu, comb, sw), N),
            ("comb_hoisted", lambda: bench(comb_hoisted, comb, sw), 1),
            ("encode", lambda: bench(encode, p), 1),
        ]
        print(f"\n== B={B} ==", flush=True)
        per = {}
        for name, run, n in rows:
            t = run()
            per[name] = t / n
            print(
                f"{name:14s} total={t * 1e3:8.2f}ms  per-unit={t / n * 1e6:9.1f}us",
                flush=True,
            )
        # reconstruct the full kernel from parts:
        #   256 doublings (as 256/N dbl_chain units of N) + 64 cached adds
        #   (~8/7 of a mul-dominated unit; approximate with mul_chain cost
        #   x 8 muls) + 64 selects + 64 comb steps + 64 mixed adds + encode
        est = (
            256 * per["dbl_chain"]
            + 64 * (8 * per["mul_chain"])
            + 64 * per["select_h"]
            + 64 * per["comb_mxu"]
            + 64 * (7 * per["mul_chain"])
            + per["encode"]
        )
        print(f"reconstructed-from-parts ~= {est * 1e3:.1f}ms", flush=True)

    trace_dir = os.environ.get("STELLARD_PROFILE_TRACE")
    if trace_dir:
        z = np.load("/tmp/sigset.npz")
        B = 4096
        inputs = ej.prepare_batch(
            [z["pubs"][i].tobytes() for i in range(B)],
            [z["msgs"][i].tobytes() for i in range(B)],
            [z["sigs"][i].tobytes() for i in range(B)],
        )
        out = ej.verify_kernel(**inputs)
        out.block_until_ready()
        with jax.profiler.trace(trace_dir):
            out = ej.verify_kernel(**inputs)
            out.block_until_ready()
        print(f"trace written to {trace_dir}", flush=True)


if __name__ == "__main__":
    bs = [int(a) for a in sys.argv[1:]] or [4096]
    main(bs)
