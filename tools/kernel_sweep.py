"""On-chip measurement sweep: verify kernel (batch x unroll) + tree hashing.

Run on a host with the TPU tunnel up (`python tools/kernel_sweep.py`).
Each configuration runs in a SUBPROCESS so a wedged tunnel session can
never kill the whole sweep (see PERF.md for why that matters here), and
the signed test set is cached on disk so retries are cheap.
"""
import os, sys, time, subprocess

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
import numpy as np
sys.path.insert(0, REPO)

CACHE = "/tmp/sigset.npz"

SIGSET_N = 16384  # must cover 2x the largest swept batch for input cycling

# measured (unroll, comb, batch, rate) rows; the winner is persisted to
# KERNEL_TUNING.json so an unattended bench.py run (the driver's
# end-of-round invocation) picks the tuned kernel without a human in
# the loop
RESULTS: list[dict] = []
TUNING_PATH = os.path.join(REPO, "KERNEL_TUNING.json")


def ensure_sigset():
    if os.path.exists(CACHE):
        if len(np.load(CACHE)["pubs"]) >= SIGSET_N:
            return
        os.remove(CACHE)  # stale smaller cache: would re-enable memoization
    from stellard_tpu.protocol.keys import KeyPair
    rng = np.random.default_rng(0)
    keys = [KeyPair.from_seed(bytes(rng.integers(0,256,32,dtype=np.uint8))) for _ in range(64)]
    N = SIGSET_N
    msgs = [bytes(rng.integers(0,256,32,dtype=np.uint8)) for _ in range(N)]
    sigs = [keys[i%64].sign(msgs[i]) for i in range(N)]
    pubs = [keys[i%64].public for i in range(N)]
    np.savez(CACHE,
             pubs=np.frombuffer(b"".join(pubs), np.uint8).reshape(N,32),
             msgs=np.frombuffer(b"".join(msgs), np.uint8).reshape(N,32),
             sigs=np.frombuffer(b"".join(sigs), np.uint8).reshape(N,64))

def one_config(unroll, batches, comb="mxu", hoist=0, group=0, impl="xla",
               block=512, check="bytes", wire="raw"):
    """Run one (unroll, comb-select, hoist, group, impl, check, batches)
    measurement in a SUBPROCESS so each tunnel session is fresh and a
    wedge can't kill the sweep. Inputs are cycled across distinct sets
    so no layer can memoize identical submissions. impl="pallas" runs
    the whole-verify-in-VMEM kernel (ops/ed25519_pallas.py) with grid
    block size `block`; check="point" runs the inversion-free projective
    final check (stacked double-width decompress)."""
    code = f'''
import os, sys, time
import numpy as np
os.environ.pop("JAX_PLATFORMS", None)
os.environ["STELLARD_VERIFY_UNROLL"] = "{unroll}"
os.environ["STELLARD_COMB_SELECT"] = "{comb}"
os.environ["STELLARD_HOIST_SELECT"] = "{hoist}"
os.environ["STELLARD_GROUP_OPS"] = "{group}"
os.environ["STELLARD_PALLAS_BLOCK"] = "{block}"
os.environ["STELLARD_VERIFY_CHECK"] = "{check}"
os.environ["STELLARD_WIRE"] = "{wire}"
sys.path.insert(0, {REPO!r})
import jax
if os.environ.get("STELLARD_SWEEP_ALLOW_CPU") != "1":
    assert jax.devices()[0].platform != "cpu", "no tpu"
from stellard_tpu.utils.xlacache import enable_compilation_cache
enable_compilation_cache()
from stellard_tpu.ops.ed25519_jax import prepare_batch
if "{impl}" == "pallas":
    from stellard_tpu.ops.ed25519_pallas import (
        verify_kernel_pallas as verify_kernel)
else:
    from stellard_tpu.ops.ed25519_jax import verify_kernel
z = np.load("{CACHE}")
N = len(z["pubs"])
for batch in {batches}:
    sets = []
    if batch <= N:
        orderings = [np.arange(s0, s0 + batch)
                     for s0 in range(0, min(4 * batch, N), batch)
                     if s0 + batch <= N]
    else:
        # batch exceeds the cached sigset: tile it and use distinct
        # permutations so no layer ever sees two identical submissions
        reps = -(-batch // N)
        base = np.tile(np.arange(N), reps)[:batch]
        rng = np.random.default_rng(0)
        orderings = [base, rng.permutation(base)]
    for idx in orderings:
        sets.append(prepare_batch(
            [z["pubs"][i].tobytes() for i in idx],
            [z["msgs"][i].tobytes() for i in idx],
            [z["sigs"][i].tobytes() for i in idx],
        ))
    t0=time.time(); out = verify_kernel(**sets[0]); out.block_until_ready()
    print(f"unroll={unroll} comb={comb} hoist={hoist} group={group} impl={impl} block={block} check={check} wire={wire} batch={{batch}} compile {{time.time()-t0:.0f}}s", flush=True)
    assert np.asarray(out).all()
    t0=time.time(); n=0
    while time.time()-t0 < 5:
        verify_kernel(**sets[n % len(sets)]).block_until_ready(); n+=1
    dt=(time.time()-t0)/n
    print(f"RESULT unroll={unroll} comb={comb} hoist={hoist} group={group} impl={impl} block={block} check={check} wire={wire} batch={{batch}} lat={{dt*1000:.1f}}ms rate={{batch/dt:,.0f}} sigs/s", flush=True)
'''
    try:
        r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                           text=True, timeout=1500)
    except subprocess.TimeoutExpired:
        print(f"unroll={unroll} comb={comb} hoist={hoist} group={group} "
              f"impl={impl} block={block} check={check} wire={wire} batches={batches}: TIMED OUT "
              f"(wedged tunnel?) — skipping", flush=True)
        return False
    out = "\n".join(l for l in (r.stdout + r.stderr).splitlines()
                    if "WARNING" not in l and l.strip())
    print(out, flush=True)
    for line in out.splitlines():
        # RESULT unroll=U comb=C batch=B lat=L rate=R sigs/s
        if line.startswith("RESULT unroll="):
            try:
                kv = dict(p.split("=", 1) for p in line.split()[1:-1]
                          if "=" in p)
                RESULTS.append({
                    "unroll": int(kv["unroll"]),
                    "comb": kv["comb"],
                    "hoist": int(kv.get("hoist", 0)),
                    "group": int(kv.get("group", 0)),
                    "impl": kv.get("impl", "xla"),
                    "block": int(kv.get("block", 512)),
                    "check": kv.get("check", "bytes"),
                    "wire": kv.get("wire", "digits"),
                    "batch": int(kv["batch"]),
                    "rate": float(kv["rate"].replace(",", "")),
                })
            except (KeyError, ValueError):
                pass
    return r.returncode == 0

def tree_hash_bench():
    code = f'''
import os, sys, time
import numpy as np
os.environ.pop("JAX_PLATFORMS", None)
sys.path.insert(0, {REPO!r})
import jax
if os.environ.get("STELLARD_SWEEP_ALLOW_CPU") != "1":
    assert jax.devices()[0].platform != "cpu", "no tpu"
from stellard_tpu.utils.xlacache import enable_compilation_cache
enable_compilation_cache()
from stellard_tpu.crypto.backend import make_hasher
from stellard_tpu.state.shamap import SHAMap, SHAMapItem, TNType

def build(n, seed):
    rng = np.random.default_rng(seed)
    m = SHAMap(TNType.ACCOUNT_STATE)
    for i in range(n):
        m.set_item(SHAMapItem(rng.bytes(32), rng.bytes(int(rng.integers(40, 600)))))
    return m

for n_leaves in (1000, 5000):
    for name in ("cpu", "tpu"):
        h = make_hasher(name)
        m = build(n_leaves, n_leaves)
        m.hash_batch = h
        t0=time.time(); m.get_hash(); c=time.time()-t0
        m2 = build(n_leaves, n_leaves + 1)
        m2.hash_batch = h
        t0=time.time(); m2.get_hash(); dt=time.time()-t0
        print(f"RESULT treehash backend={{name}} leaves={{n_leaves}} first={{c:.2f}}s warm={{dt*1000:.0f}}ms", flush=True)
'''
    try:
        r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                           text=True, timeout=1500)
    except subprocess.TimeoutExpired:
        print("treehash bench TIMED OUT — skipping", flush=True)
        return
    print("\n".join(l for l in (r.stdout+r.stderr).splitlines()
                    if "WARNING" not in l and l.strip()), flush=True)
    if r.returncode != 0 or "RESULT treehash" not in r.stdout:
        # a silent miss here cost two windows of the one unmeasured
        # number the replay leg hinges on — make the failure loud
        print("treehash bench FAILED (no RESULT rows)", flush=True)

def transfer_probe():
    """Host->device transfer rate for one prepared verify batch — the
    e2e headline's unexplained gap (14.5k e2e vs 96.6k device-only in
    the contaminated r4 window) points at the tunnel's transfer path;
    this measures it directly, for the narrow (int8 digit) wire format."""
    code = f'''
import os, sys, time
import numpy as np
os.environ.pop("JAX_PLATFORMS", None)
sys.path.insert(0, {REPO!r})
import jax
if os.environ.get("STELLARD_SWEEP_ALLOW_CPU") != "1":
    assert jax.devices()[0].platform != "cpu", "no tpu"
from stellard_tpu.ops.ed25519_jax import prepare_batch
import jax.numpy as jnp
z = np.load("{CACHE}")
B = 16384
idx = list(range(B))
for wire in ("raw", "digits"):
    os.environ["STELLARD_WIRE"] = wire
    inputs = prepare_batch(
        [z["pubs"][i % len(z["pubs"])].tobytes() for i in idx],
        [z["msgs"][i % len(z["msgs"])].tobytes() for i in idx],
        [z["sigs"][i % len(z["sigs"])].tobytes() for i in idx],
        device_put=False,
    )
    nbytes = sum(np.asarray(v).nbytes for v in inputs.values())
    # one warm put, then timed puts of fresh host copies
    for _ in range(2):
        res = {{k: jnp.asarray(v) for k, v in inputs.items()}}
        jax.block_until_ready(list(res.values()))
    t0 = time.time(); n = 0
    while time.time() - t0 < 5:
        res = {{k: jnp.asarray(np.ascontiguousarray(v)) for k, v in inputs.items()}}
        jax.block_until_ready(list(res.values()))
        n += 1
    dt = (time.time() - t0) / n
    print(f"RESULT transfer wire={{wire}} batch={{B}} bytes={{nbytes}} per_put={{dt*1000:.1f}}ms rate={{nbytes/dt/1e6:.1f}} MB/s", flush=True)
'''
    try:
        r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                           text=True, timeout=900)
    except subprocess.TimeoutExpired:
        print("transfer probe TIMED OUT — skipping", flush=True)
        return
    print("\n".join(l for l in (r.stdout + r.stderr).splitlines()
                    if "WARNING" not in l and l.strip()), flush=True)
    if r.returncode != 0 or "RESULT transfer" not in r.stdout:
        print("transfer probe FAILED (no RESULT row)", flush=True)


def write_tuning():
    if not RESULTS:
        return
    import json

    # merge with the existing tuning history: a partial sweep (wedged
    # tunnel) must never bury a better configuration measured earlier —
    # the winner is the best across ALL recorded rows, deduped by config
    rows = list(RESULTS)
    try:
        with open(TUNING_PATH) as f:
            prior = json.load(f).get("all", [])
    except (OSError, ValueError):
        prior = []
    def key(r):
        return (r.get("unroll", 1), r.get("comb", "mxu"),
                r.get("hoist", 0), r.get("group", 0),
                r.get("impl", "xla"), r.get("block", 512),
                r.get("check", "bytes"), r.get("wire", "digits"),
                r.get("batch"))
    seen = {key(r) for r in rows}
    for r in prior:
        # normalize historical source-revision labels: "rowpad" IS the
        # current xla kernel (hoist=0/group=0); "legacy" rows measured
        # superseded source and are dropped
        impl = r.get("impl", "xla")
        if impl == "legacy":
            continue
        if impl == "rowpad":
            r = {**r, "impl": "xla", "hoist": 0, "group": 0}
        if key(r) not in seen:      # keep older rows not re-measured
            rows.append(r)
            seen.add(key(r))
    # the persisted WINNER must keep the reference verify semantics:
    # check=point rows are recorded in "all" for the A/B evidence, but
    # auto-applied tuning never flips the consensus-critical check mode
    # (see crypto.backend.apply_kernel_tuning) — so the winner is the
    # best bytes-mode row
    bytes_rows = [r for r in rows if r.get("check", "bytes") == "bytes"]
    best = max(bytes_rows or rows, key=lambda r: r["rate"])
    RESULTS[:] = rows
    # temp + rename: an interrupted dump must never leave a truncated
    # file for the driver's unattended bench.py to trip over. The file
    # is committed with the round like the other bench artifacts — it
    # documents the measured-best kernel config.
    tmp = TUNING_PATH + ".tmp"
    with open(tmp, "w") as f:
        json.dump({
            "unroll": best["unroll"],
            "comb": best["comb"],
            "hoist": best.get("hoist", 0),
            "group": best.get("group", 0),
            "impl": best.get("impl", "xla"),
            "block": best.get("block", 512),
            "check": best.get("check", "bytes"),
            # a row measured before the wire field existed carries NO
            # wire opinion — writing "digits" here would drag the bench
            # back to the fat wire via apply_kernel_tuning
            **({"wire": best["wire"]} if "wire" in best else {}),
            "batch": best["batch"],
            "rate": best["rate"],
            "all": RESULTS,
            "note": "measured by tools/kernel_sweep.py on the current "
                    "kernel source (rowpad fe_mul; hoist/group gates; "
                    "impl=xla|pallas)",
        }, f, indent=1)
    os.replace(tmp, TUNING_PATH)
    print(f"TUNING -> {TUNING_PATH}: unroll={best['unroll']} "
          f"comb={best['comb']} batch={best['batch']} "
          f"rate={best['rate']:,.0f}", flush=True)


if __name__ == "__main__":
    # Config list as of the rowpad + hoisted-select kernel (measured
    # r4: rowpad in-loop-select hit 46.3k/71.6k/99.9k/103.4k sigs/s at
    # 4096/8192/16384/32768; unroll>1 measured flat, so the sweep
    # focuses on batch scaling + comb A/B for the hoisted form).
    ensure_sigset()
    # Measured 2026-07-31 (SWEEP_r04.log): hoist=0/group=0 @16384 =
    # 100.7k sigs/s (reproduces the a7910e1 winner); group=1 = 63.2k
    # (grouping is the regression); hoisted+grouped = 63.7k. Standing
    # record: 103.4k @32768 (prior window). Remaining questions,
    # ordered so a short window answers the biggest first:
    # 1) the raw-bytes wire on the known winner config (the e2e
    #    headline's transfer leg: 129 B/sig vs 193; kernel math
    #    unchanged, so rate should match the 103.4k record while e2e
    #    improves), then the digits wire as the A/B control:
    one_config(1, [16384, 32768], wire="raw")
    write_tuning()
    # 2) the inversion-free projective final check (~15% fewer
    #    sequential wide ops than the ref10 byte-compare shape):
    one_config(1, [16384, 32768], check="point")
    # 2) the Pallas whole-verify-in-VMEM kernel vs the XLA formulation
    #    (same block set for both check modes — the comparison must not
    #    confound formulation with block size):
    one_config(1, [16384], impl="pallas", block=512)
    write_tuning()  # interim: a wedge below must not lose what's measured
    # 2b) host->device transfer rate (is the e2e headline
    #     transfer-bound over the tunnel?)
    transfer_probe()
    # 3) tree-hash first/warm timings — NEVER yet measured on-chip
    #    (dropped by wedges in both r4 windows) and the replay leg's
    #    device share hinges on them; ahead of the remaining verify A/Bs
    tree_hash_bench()
    one_config(1, [16384], impl="pallas", block=1024)
    write_tuning()  # interim after every late config: the 5400s outer
    one_config(1, [16384], impl="pallas", block=512, check="point")
    write_tuning()  # deadline must never lose a completed measurement
    # 4) batch scaling of the XLA winner beyond the 32768 record:
    one_config(1, [32768, 65536], group=0)
    write_tuning()
    # 5) consensus-close-sized batches (VERDICT r4 #8): can ANY device
    #    config beat threaded-native at ~300-2048 sigs? Pallas small
    #    blocks are the candidate; the XLA row is the control. If both
    #    lose to the host at these sizes, the router's CPU floor on the
    #    close leg is the measured-optimal answer and PERF.md says so.
    one_config(1, [512, 2048], impl="pallas", block=256)
    write_tuning()
    one_config(1, [512, 2048])
    write_tuning()
    # 6) in-loop comb-select strategies at the winning defaults:
    one_config(1, [16384], comb="mxu_split")
    write_tuning()
    one_config(1, [16384], comb="vpu")
    write_tuning()
    print("SWEEP DONE", flush=True)
