"""Mesh-throughput bench: the sharded verify step on N virtual CPU devices.

Run as a SUBPROCESS (the host process usually has a JAX backend already
initialised; the device-count flag must be set before init). Prints one
JSON line:

  {"mesh_devices": N, "batch": B, "mesh_rate": r, "single_rate": r1,
   "scaling": r/r1}

On this 1-core build box the N virtual devices time-slice one core, so
`scaling` ~1.0 is healthy; the leg exists to (a) keep the
`parallel/mesh.py` sharded path exercised with a throughput number every
round so a sharding/collective regression shows up as a number, not just
a dryrun pass/fail, and (b) report real scaling when run on multi-core
hosts or a real mesh. Reference seam: SURVEY §2.9 mapping #3 (ICI
data-parallel verify, the NCCL-role replacement).
"""

from __future__ import annotations

import json
import os
import re
import sys
import time

N = int(os.environ.get("MESH_BENCH_DEVICES", "8"))
BATCH = int(os.environ.get("MESH_BENCH_BATCH", "2048"))
SECONDS = float(os.environ.get("MESH_BENCH_SECONDS", "5"))

opt = f"--xla_force_host_platform_device_count={N}"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" in flags:
    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", opt, flags)
else:
    flags = (flags + " " + opt).strip()
os.environ["XLA_FLAGS"] = flags
os.environ["JAX_PLATFORMS"] = "cpu"

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from stellard_tpu.ops.ed25519_jax import prepare_batch, verify_kernel
    from stellard_tpu.parallel.mesh import make_mesh, verify_and_count
    from stellard_tpu.protocol.keys import KeyPair

    rng = np.random.default_rng(3)
    keys = [KeyPair.from_seed(bytes(rng.integers(0, 256, 32, dtype=np.uint8)))
            for _ in range(16)]
    msgs = [bytes(rng.integers(0, 256, 32, dtype=np.uint8))
            for _ in range(BATCH)]
    sigs = [keys[i % 16].sign(msgs[i]) for i in range(BATCH)]
    pubs = [keys[i % 16].public for i in range(BATCH)]
    inp = prepare_batch(pubs, msgs, sigs)
    args = (inp["a_words"], inp["r_words"], inp["s_windows"],
            inp["h_digits"], inp["s_canonical"])

    devices = [d for d in jax.devices() if d.platform == "cpu"][:N]
    assert len(devices) == N, f"need {N} cpu devices, have {jax.devices()}"
    mesh = make_mesh(devices)
    step = verify_and_count(mesh)

    flags_out, total = step(*args)
    flags_out.block_until_ready()  # compile
    assert int(total) == BATCH, (int(total), BATCH)
    t0 = time.time()
    n = 0
    while time.time() - t0 < SECONDS:
        f, _ = step(*args)
        f.block_until_ready()
        n += 1
    mesh_rate = BATCH * n / (time.time() - t0)

    out = verify_kernel(**inp)
    out.block_until_ready()  # compile
    t0 = time.time()
    n = 0
    while time.time() - t0 < SECONDS:
        verify_kernel(**inp).block_until_ready()
        n += 1
    single_rate = BATCH * n / (time.time() - t0)

    # the hashing twin: mesh-sharded masked SHA-512 over SHAMap-node-
    # sized payloads (parallel/mesh.py sharded_masked_sha512)
    import hashlib

    from stellard_tpu.ops.sha512_jax import padded_block_count
    from stellard_tpu.ops.treehash_jax import pad_leaf_batch
    from stellard_tpu.parallel.mesh import sharded_masked_sha512

    payloads = [bytes(rng.integers(0, 256, int(sz), dtype=np.uint8))
                for sz in rng.integers(64, 600, 1024)]
    ladder = max(padded_block_count(len(p)) for p in payloads)
    blocks, nblocks = pad_leaf_batch(payloads, ladder)
    hasher = sharded_masked_sha512(mesh)
    state = np.asarray(hasher(blocks, nblocks))  # compile
    assert state[0].astype(">u4").tobytes() == hashlib.sha512(
        payloads[0]).digest()
    t0 = time.time()
    n = 0
    while time.time() - t0 < SECONDS:
        hasher(blocks, nblocks).block_until_ready()
        n += 1
    hash_rate = len(payloads) * n / (time.time() - t0)

    print(json.dumps({
        "mesh_devices": N,
        "batch": BATCH,
        "mesh_rate": round(mesh_rate, 1),
        "single_rate": round(single_rate, 1),
        "scaling": round(mesh_rate / single_rate, 3) if single_rate else 0.0,
        "mesh_hash_nodes_per_sec": round(hash_rate, 1),
    }))


if __name__ == "__main__":
    main()
