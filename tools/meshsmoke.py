#!/usr/bin/env python3
"""Multi-chip smoke gate (tools/tier1.sh).

Boots a standalone node with the SIGNATURE plane mesh-enabled on the
virtual 8-device CPU mesh ([signature_backend] type=tpu mesh=auto
routing=device), floods 200 payments through the full async pipeline
closing every 50, then replays the IDENTICAL deterministic workload on
a cpu-backend node. Gates:

- ledger-hash byte identity at every close between the two runs (a
  sharded verifier that flipped one verdict would fork the chain here,
  not in a consensus round);
- device_sigs > 0 and an effective mesh width of 8 on the mesh run
  (anti-vacuity: routing honesty means the gate fails when the "mesh"
  run silently verified on the host);
- the fused whole-tree hash pipeline ran ([hash_backend] type=tpu
  routing=device) and read back from the device exactly ONCE per tree
  (transfer honesty: a per-level round-trip is a residency regression);
- zero rejected transactions in either run.

Exit 0 on all gates; 1 otherwise.
"""

from __future__ import annotations

import os
import re
import sys

# the virtual mesh must exist BEFORE jax initializes (same contract as
# tests/conftest.py); runnable as `python tools/meshsmoke.py`
opt = "--xla_force_host_platform_device_count=8"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" in flags:
    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", opt, flags)
else:
    flags = (flags + " " + opt).strip()
os.environ["XLA_FLAGS"] = flags
os.environ["JAX_PLATFORMS"] = "cpu"
# bounded compile budget: ONE padded shape (pad-to-max at 256) for the
# whole flood, measured XLA formulation — never pallas-interpret
os.environ["STELLARD_PAD_POLICY"] = "max"
os.environ["STELLARD_VERIFY_IMPL"] = "xla"

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def drive(cfg, n_txs: int = 200):
    """Deterministic flood: same keys/seqs/amounts per run; returns
    ([(seq, ledger_hash)...], {verify, hash} plane jsons, rejected
    count)."""
    import threading

    from stellard_tpu.node.node import Node
    from stellard_tpu.protocol.formats import TxType
    from stellard_tpu.protocol.keys import KeyPair
    from stellard_tpu.protocol.sfields import sfAmount, sfDestination
    from stellard_tpu.protocol.stamount import STAmount
    from stellard_tpu.protocol.sttx import SerializedTransaction

    node = Node(cfg).setup()
    try:
        if node.verify_prewarm is not None:
            node.verify_prewarm.join(timeout=600)
        master = KeyPair.from_passphrase("masterpassphrase")
        dests = [
            KeyPair.from_passphrase(f"mesh-smoke-{i}").account_id
            for i in range(8)
        ]
        done = threading.Semaphore(0)
        rejected = []

        def cb(tx, ter, applied):
            if not applied:
                rejected.append(ter)
            done.release()

        closes = []
        for chunk in range(0, n_txs, 50):
            txs = []
            for i in range(chunk, min(chunk + 50, n_txs)):
                tx = SerializedTransaction.build(
                    TxType.ttPAYMENT, master.account_id, 1 + i, 10,
                    {sfAmount: STAmount.from_drops(250_000_000),
                     sfDestination: dests[i % len(dests)]},
                )
                tx.sign(master)
                txs.append(tx)
            for tx in txs:
                node.ops.submit_transaction(tx, cb)
            for _ in txs:
                done.acquire()
            closed, _results = node.ops.accept_ledger()
            closes.append((closed.seq, closed.hash()))
        hj = getattr(node.hasher, "get_json", None)
        planes = {
            "verify": node.verify_plane.get_json(),
            "hash": hj() if hj is not None else None,
        }
        return closes, planes, len(rejected)
    finally:
        node.stop()


def run_smoke() -> int:
    from stellard_tpu.node.config import Config
    from stellard_tpu.utils.xlacache import enable_compilation_cache

    enable_compilation_cache()

    mesh_closes, planes, mesh_rejected = drive(Config(
        signature_backend="tpu",
        verify_mesh="auto",
        verify_routing="device",
        verify_min_device_batch=1,
        verify_max_batch=256,
        # hash plane on the same virtual mesh, device-forced: the
        # fused whole-tree pipeline must carry the close's tree work
        # so the transfer gate below is non-vacuous
        hash_backend="tpu",
        hash_mesh="auto",
        hash_routing="device",
        hash_min_device_nodes=0,
        kernel_tuning="none",
    ))
    vp = planes["verify"]
    cpu_closes, _planes_cpu, cpu_rejected = drive(Config(
        signature_backend="cpu",
        kernel_tuning="none",
    ))

    bad = 0
    if mesh_rejected or cpu_rejected:
        print(f"mesh smoke: rejected txs (mesh={mesh_rejected} "
              f"cpu={cpu_rejected})", file=sys.stderr)
        bad += 1
    if len(mesh_closes) != len(cpu_closes):
        print(f"mesh smoke: close count mismatch {len(mesh_closes)} vs "
              f"{len(cpu_closes)}", file=sys.stderr)
        bad += 1
    for (ms, mh), (cs, ch) in zip(mesh_closes, cpu_closes):
        if ms != cs or mh != ch:
            print(f"mesh smoke: ledger DIVERGED at seq {ms}/{cs}: "
                  f"{mh.hex()[:16]} vs {ch.hex()[:16]}", file=sys.stderr)
            bad += 1
    # anti-vacuity: the mesh leg must have verified on the sharded
    # device plane, at the full virtual width, without a wedge fallback
    mesh_info = vp.get("mesh") or {}
    if not vp.get("device_sigs"):
        print(f"mesh smoke: device_sigs=0 — the mesh run verified on "
              f"the host (routing={vp.get('routing')}, "
              f"wedged={vp.get('device_wedged')})", file=sys.stderr)
        bad += 1
    if mesh_info.get("mesh_width") != 8:
        print(f"mesh smoke: effective width {mesh_info.get('mesh_width')}"
              f" != 8 (kernel={mesh_info.get('kernel')})", file=sys.stderr)
        bad += 1
    # fused-close transfer honesty (ISSUE 16): the whole-tree pipeline
    # ran, and it read back from the device exactly ONCE per tree — a
    # readback count above tree_pipeline_calls means some level quietly
    # round-tripped to the host mid-chain (residency regression)
    hp = planes.get("hash") or {}
    hmesh = hp.get("mesh") or {}
    tree_calls = hmesh.get("tree_pipeline_calls") or 0
    tree_tr = hmesh.get("tree_transfers") or {}
    if not tree_calls:
        print(f"mesh smoke: tree_pipeline_calls=0 — the fused hash "
              f"pipeline never ran (wedged={hp.get('wedged')}, "
              f"tree_kernel={hmesh.get('tree_kernel')})", file=sys.stderr)
        bad += 1
    elif tree_tr.get("readbacks") != tree_calls:
        print(f"mesh smoke: {tree_tr.get('readbacks')} device readbacks "
              f"over {tree_calls} fused trees — expected exactly one "
              f"per tree", file=sys.stderr)
        bad += 1
    if bad:
        return 1
    print(
        f"mesh smoke OK: {len(mesh_closes)} closes byte-identical "
        f"mesh-vs-cpu, device_sigs={vp['device_sigs']} over "
        f"width={mesh_info.get('mesh_width')} "
        f"({mesh_info.get('kernel')}, routing={vp.get('routing')}); "
        f"fused trees={tree_calls} readbacks={tree_tr.get('readbacks')} "
        f"({hmesh.get('tree_kernel')})"
    )
    return 0


if __name__ == "__main__":
    sys.exit(run_smoke())
