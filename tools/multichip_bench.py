"""Multichip bench: mesh width as a CONFIG axis, swept end to end.

Unlike tools/mesh_bench.py (which drives parallel/mesh.py kernels
directly), this leg sweeps the PRODUCT seam the node itself uses —
``make_verifier("tpu", mesh=W)`` and ``make_watched_hasher("tpu",
mesh=W, routing="device")`` — over widths 1/2/4/8 of a virtual CPU
mesh, measuring verify sigs/s and packed tree-hash nodes/s per width
and pinning byte identity against the host reference at EVERY width.

Run as a SUBPROCESS (the device-count flag must be set before backend
init). Prints one JSON line; bench.py's bench_multichip() wraps it
into BENCH metric lines with honest fallback/provenance fields: on
this box the "devices" are virtual CPU shards, and the line says so —
a CPU-emulated sweep must never masquerade as a chip number
(BENCH_r04's lesson).

``--gate-sigs-per-sec N`` turns the sweep into a CI perf gate: exit
nonzero when the best verify rate lands below the bar — but ONLY on
real (non-virtual, non-CPU) devices. A virtual CPU mesh measures
sharding overhead, not chip throughput, so the gate records itself as
ungated there instead of failing a box that can't possibly pass
(provenance: the "gate" block always says whether it was armed).
"""

from __future__ import annotations

import json
import os
import re
import sys
import time

N = int(os.environ.get("MULTICHIP_DEVICES", "8"))
WIDTHS = [int(w) for w in
          os.environ.get("MULTICHIP_WIDTHS", "1,2,4,8").split(",")]
BATCH = int(os.environ.get("MULTICHIP_BATCH", "512"))
HASH_NODES = int(os.environ.get("MULTICHIP_HASH_NODES", "2048"))
SECONDS = float(os.environ.get("MULTICHIP_SECONDS", "3"))
TREE_REPS = int(os.environ.get("MULTICHIP_TREE_REPS", "3"))

opt = f"--xla_force_host_platform_device_count={N}"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" in flags:
    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", opt, flags)
else:
    flags = (flags + " " + opt).strip()
os.environ["XLA_FLAGS"] = flags
os.environ["JAX_PLATFORMS"] = "cpu"
# the sweep measures the XLA formulation (the tuned production default);
# pallas-interpret on a CPU mesh measures the interpreter, not the plane
os.environ.setdefault("STELLARD_VERIFY_IMPL", "xla")
# one compiled shape per width: every chunk pads to max_batch
os.environ.setdefault("STELLARD_PAD_POLICY", "max")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(gate_sigs_per_sec: float | None = None) -> int:
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from stellard_tpu.crypto.backend import (
        CpuHasher,
        VerifyRequest,
        make_verifier,
        make_watched_hasher,
    )
    from stellard_tpu.protocol.keys import KeyPair
    from stellard_tpu.utils.xlacache import enable_compilation_cache

    enable_compilation_cache()
    devices = jax.devices()
    widths = sorted({min(w, len(devices)) for w in WIDTHS})

    # -- verify workload: ragged batch, bad signatures planted in every
    #    shard position of the widest mesh ------------------------------
    rng = np.random.default_rng(7)
    keys = [KeyPair.from_seed(bytes(rng.integers(0, 256, 32,
                                                 dtype=np.uint8)))
            for _ in range(16)]
    n_sigs = BATCH - 3  # ragged: not divisible by any width
    corrupt = set(range(0, n_sigs, max(1, n_sigs // max(widths))))
    reqs, want = [], []
    for i in range(n_sigs):
        k = keys[i % 16]
        m = bytes(rng.integers(0, 256, 32, dtype=np.uint8))
        s = bytearray(k.sign(m))
        if i in corrupt:
            s[int(rng.integers(0, 64))] ^= 1 << int(rng.integers(0, 8))
        reqs.append(VerifyRequest(k.public, m, bytes(s)))
        want.append(i not in corrupt)
    want = np.array(want, bool)

    verify = {}
    for w in widths:
        v = make_verifier("tpu", mesh=str(w), min_batch=BATCH,
                          max_batch=BATCH)
        got = np.asarray(v.verify_batch(reqs))  # compile + identity
        identical = bool(np.array_equal(got, want))
        t0 = time.time()
        n = 0
        while time.time() - t0 < SECONDS:
            r = np.asarray(v.verify_batch(reqs))
            identical = identical and bool(np.array_equal(r, want))
            n += 1
        rate = n_sigs * n / (time.time() - t0)
        verify[str(w)] = {
            "sigs_per_sec": round(rate, 1),
            "identical_every_rep": identical,
            **v.describe(),
        }

    # -- hash workload: the packed flat-buffer shape (pack_nodes /
    #    seal-flush contract: blob == hashed bytes), routed through the
    #    SAME watched construction the node runs -----------------------
    msgs = []
    for _ in range(HASH_NODES):
        size = int(rng.integers(40, 300))
        msgs.append(b"MIN\0" + bytes(rng.integers(0, 256, size,
                                                  dtype=np.uint8)))
    buf = b"".join(msgs)
    offsets = [0]
    for m in msgs:
        offsets.append(offsets[-1] + len(m))
    host_ref = CpuHasher().hash_packed(buf, offsets)

    hashp = {}
    for w in widths:
        h = make_watched_hasher("tpu", mesh=str(w), routing="device",
                                min_device_nodes=0)
        got = h.hash_packed(buf, offsets)  # compile + identity
        identical = got == host_ref
        t0 = time.time()
        n = 0
        while time.time() - t0 < SECONDS:
            r = h.hash_packed(buf, offsets)
            identical = identical and (r == host_ref)
            n += 1
        rate = HASH_NODES * n / (time.time() - t0)
        j = h.get_json()
        hashp[str(w)] = {
            "nodes_per_sec": round(rate, 1),
            "identical_every_rep": bool(identical),
            "device_nodes": j["device_nodes"],
            "mesh": j["mesh"],
            "cost_model": j["flat_model"],
        }

    # -- fused whole-tree sweep: the device-resident close pipeline at
    #    every width, identity against the host oracle AND the staged
    #    (fused=0) device path, one readback per tree enforced ----------
    from stellard_tpu.state.shamap import SHAMap, SHAMapItem, TNType

    def build_tree(seed: int) -> SHAMap:
        r = np.random.default_rng(seed)
        m = SHAMap(TNType.ACCOUNT_STATE)
        for _ in range(max(64, HASH_NODES // 8)):
            m.set_item(SHAMapItem(r.bytes(32),
                                  r.bytes(int(r.integers(40, 300)))))
        return m

    host_roots = []
    for rep in range(TREE_REPS):
        m = build_tree(100 + rep)
        m.hash_batch = CpuHasher()
        host_roots.append(m.get_hash())

    tree = {}
    for w in widths:
        h = make_watched_hasher("tpu", mesh=str(w), routing="device",
                                min_device_nodes=0)
        ok_fused = ok_staged = True
        t_hash = 0.0
        nodes = 0
        for rep in range(TREE_REPS):
            m = build_tree(100 + rep)
            m.hash_batch = h
            t0 = time.time()
            root = m.get_hash()
            t_hash += time.time() - t0
            ok_fused = ok_fused and (root == host_roots[rep])
            nodes += max(64, HASH_NODES // 8)
        sh = make_watched_hasher("tpu", mesh=str(w), routing="device",
                                 min_device_nodes=0)
        sh.fused_enabled = False  # the [tree] fused=0 kill-switch path
        sm = build_tree(100)
        sm.hash_batch = sh
        ok_staged = sm.get_hash() == host_roots[0]
        j = h.get_json()["mesh"] or {}
        tt = j.get("tree_transfers") or {}
        tree[str(w)] = {
            "nodes_per_sec": round(nodes / t_hash, 1) if t_hash else None,
            "fused_identical_every_rep": bool(ok_fused),
            "staged_identical": bool(ok_staged),
            "tree_kernel": j.get("tree_kernel"),
            "tree_width": j.get("tree_width"),
            "tree_calls": j.get("tree_pipeline_calls"),
            "readbacks": tt.get("readbacks"),
            "one_readback_per_tree": (
                tt.get("readbacks") == j.get("tree_pipeline_calls")
            ),
        }

    # -- perf gate: armed only on real accelerators ---------------------
    best = max(v["sigs_per_sec"] for v in verify.values())
    real_devices = devices[0].platform not in ("cpu",)
    gate = {
        "sigs_per_sec_bar": gate_sigs_per_sec,
        "armed": bool(gate_sigs_per_sec is not None and real_devices),
        "best_sigs_per_sec": best,
    }
    if gate_sigs_per_sec is not None and not real_devices:
        gate["reason"] = (
            "virtual CPU mesh: sharding-overhead measurement, not chip "
            "throughput — gate recorded but NOT armed"
        )
    failed = bool(gate["armed"] and best < gate_sigs_per_sec)
    gate["passed"] = (not failed) if gate["armed"] else None

    print(json.dumps({
        "widths": widths,
        "virtual_devices": len(devices),
        "platform": devices[0].platform,
        "devices": [str(d) for d in devices],
        "batch": n_sigs,
        "hash_nodes": HASH_NODES,
        "verify": verify,
        "hash": hashp,
        "tree": tree,
        "gate": gate,
    }))
    if failed:
        print(
            f"multichip gate FAILED: best {best:.1f} sigs/s < bar "
            f"{gate_sigs_per_sec:.1f}", file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    import argparse

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "--gate-sigs-per-sec", type=float, default=None, metavar="N",
        help="exit nonzero when the best verify rate is below N sigs/s "
             "(armed only on real non-virtual accelerator devices; on a "
             "virtual CPU mesh the gate is recorded as unarmed)",
    )
    args = ap.parse_args()
    sys.exit(main(gate_sigs_per_sec=args.gate_sigs_per_sec))
