"""Shared net-lab helpers for the multi-process validator harnesses —
imported by tests/test_multiproc_net.py and tools/chaos_soak.py so the
config template, launcher, and RPC helper exist in exactly one place.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SPEED = 5.0  # virtual seconds per real second (clock_speed knob)


def free_ports(n: int) -> list[int]:
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def rpc(port: int, method: str, params: dict | None = None, timeout=5.0):
    body = json.dumps({"method": method, "params": [params or {}]}).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/",
        data=body,
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.load(resp)["result"]


def wait_until(pred, timeout: float, interval: float = 0.5):
    deadline = time.monotonic() + timeout
    last = None
    while time.monotonic() < deadline:
        try:
            last = pred()
            if last:
                return last
        except Exception:
            pass
        time.sleep(interval)
    return last


def validator_config(i: int, keys, peer_ports, rpc_port, ws_port=None,
                     quorum=3, speed=SPEED) -> str:
    """One validator's INI (the shape the reference's private-net
    example config documents: UNL of the OTHER validators, fixed peer
    list, quorum)."""
    n = len(keys)
    others_keys = "\n".join(
        keys[j].human_node_public for j in range(n) if j != i
    )
    others_addrs = "\n".join(
        f"127.0.0.1 {peer_ports[j]}" for j in range(n) if j != i
    )
    ws = f"\n[websocket_port]\n{ws_port}\n" if ws_port is not None else ""
    return f"""
[standalone]
0

[node_db]
type=memory

[signature_backend]
type=cpu

[validation_seed]
{keys[i].human_seed}

[validators]
{others_keys}

[validation_quorum]
{quorum}

[peer_port]
{peer_ports[i]}

[peer_ssl]
require

[ips]
{others_addrs}

[clock_speed]
{speed}

[rpc_port]
{rpc_port}
{ws}"""


def spawn_validator(cfg_path: str, stdout=subprocess.DEVNULL):
    """Launch one validator process from its config (never grabbing the
    TPU tunnel)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    return subprocess.Popen(
        [sys.executable, "-m", "stellard_tpu", "--conf", cfg_path,
         "--start"],
        cwd=REPO, env=env, stdout=stdout, stderr=subprocess.STDOUT,
    )
