"""Shared net-lab helpers for the multi-process validator harnesses.

The implementation moved into the package
(stellard_tpu/testkit/tcpnet.py) so the scenario plane's TCP runner,
tests/test_multiproc_net.py and tools/chaos_soak.py share exactly one
config template, launcher, and RPC helper; this module re-exports the
original names for the path-hacking tool scripts.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from stellard_tpu.testkit.tcpnet import (  # noqa: E402,F401
    REPO,
    SPEED,
    free_ports,
    rpc,
    run_tcp,
    spawn_validator,
    validator_config,
    wait_until,
)
