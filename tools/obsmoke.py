"""Tier-1 observability smoke: the fleet-wide observability plane as a
gate, end-to-end over real TCP.

Boots a LEADER (networked solo validator, quorum=1) and TWO FOLLOWERS,
floods the leader with sampling at 1.0 and propagation ON, and asserts
the whole PR-18 contract:

- cross-node tracing: `trace_dump` fetched from all three HTTP doors,
  merged by tools/traceview.py merge_dumps — at least one transaction's
  causal tree spans >= 3 process lanes, every cross-node parent link
  resolves, and each wide tree is single-rooted;
- propagate=0 wire identity: every trace-carrying message without a
  context encodes byte-identically to the legacy wire, and stripping a
  received context restores the legacy bytes (checked at the encoder
  seam, same pin as tests/test_trace_propagation.py);
- /metrics: the Prometheus door scrapes clean MID-FLOOD on all three
  nodes (text format 0.0.4, health gauge present), and the
  `metrics_history` admin RPC returns sampled rows;
- health + flight recorder: all three watchdogs read ok on the clean
  leg (anti-false-positive), then an INJECTED cadence stall — the
  leader is killed — flips the followers to warn and ships a
  flight-recorder dump (anti-vacuity: the gate fails if the watchdog
  sleeps through a real stall).

Runtime: ~40-70s (clock_speed-accelerated consensus).

Usage: python tools/obsmoke.py
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SPEED = 5.0
STALL_WARN_S = 4.0


def fail(msg: str) -> None:
    print(f"OBSERVABILITY SMOKE FAILED: {msg}", file=sys.stderr)
    raise SystemExit(2)


def check_wire_identity() -> None:
    """The propagate=0 pin at the encoder seam: no context -> legacy
    bytes, field 60 absent; strip a received context -> legacy bytes."""
    from stellard_tpu.overlay.proto import first, parse
    from stellard_tpu.overlay.wire import (
        TRACE_CTX_FIELD,
        GetSegments,
        MessageType,
        ProposeSet,
        SegmentData,
        TraceContext,
        TxMessage,
        ValidationMessage,
        decode_message,
        encode_message,
    )

    ctx = TraceContext(trace=bytes(range(32)), parent=(3 << 32) | 9,
                       sampled=True)
    carriers = [
        (MessageType.TRANSACTION, TxMessage(b"\x01" * 40)),
        (MessageType.PROPOSE_SET,
         ProposeSet(1, 99, b"\x02" * 32, b"\x03" * 32, b"\x04" * 33,
                    b"\x05" * 64)),
        (MessageType.VALIDATION, ValidationMessage(b"\x06" * 50)),
        (MessageType.GET_SEGMENTS, GetSegments(seg_id=1, offset=0)),
        (MessageType.SEGMENT_DATA,
         SegmentData(seg_id=1, total=10, offset=0, data=b"\x07" * 10)),
    ]
    for mt, msg in carriers:
        legacy = encode_message(msg)
        if first(parse(legacy), TRACE_CTX_FIELD) is not None:
            fail(f"{type(msg).__name__}: ctx field present with no ctx")
        msg.trace_ctx = ctx
        traced = encode_message(msg)
        if traced == legacy:
            fail(f"{type(msg).__name__}: ctx did not reach the wire")
        got = decode_message(int(mt), traced)
        got.trace_ctx = None
        if encode_message(got) != legacy:
            fail(f"{type(msg).__name__}: stripped frame not byte-identical "
                 f"to the legacy wire")


def scrape_metrics(port: int) -> str:
    req = urllib.request.Request(f"http://127.0.0.1:{port}/metrics")
    with urllib.request.urlopen(req, timeout=10) as resp:
        if resp.status != 200:
            fail(f"/metrics returned {resp.status}")
        ctype = resp.headers.get("Content-Type", "")
        if "version=0.0.4" not in ctype:
            fail(f"/metrics content-type not 0.0.4: {ctype!r}")
        return resp.read().decode("utf-8")


def check_scrape(port: int, who: str) -> None:
    text = scrape_metrics(port)
    if not text.endswith("\n"):
        fail(f"{who} /metrics payload missing final line feed")
    if "stellard_health_status 0" not in text:
        fail(f"{who} /metrics missing healthy stellard_health_status gauge")
    for line in text.splitlines():
        if not line.startswith("#") and line and " " not in line:
            fail(f"{who} /metrics malformed sample line: {line!r}")


def main() -> None:
    import tempfile

    import jax

    jax.config.update("jax_platforms", "cpu")

    from traceview import (
        fetch_dump,
        merge_dumps,
        validate_chrome_trace,
        validate_merged_trace,
    )

    from stellard_tpu.node.config import Config
    from stellard_tpu.node.node import Node
    from stellard_tpu.protocol.formats import TxType
    from stellard_tpu.protocol.keys import KeyPair
    from stellard_tpu.protocol.sfields import sfAmount, sfDestination
    from stellard_tpu.protocol.stamount import STAmount
    from stellard_tpu.protocol.sttx import SerializedTransaction
    from stellard_tpu.testkit.tcpnet import free_ports, rpc, wait_until

    check_wire_identity()

    tmp = tempfile.mkdtemp(prefix="obsmoke-")
    leader_peer, f1_peer, f2_peer = free_ports(3)
    val_key = KeyPair.from_passphrase("obsmoke-leader")

    def obs_cfg(**kw) -> Config:
        return Config(
            standalone=False,
            signature_backend="cpu",
            node_db_type="segstore",
            validation_quorum=1,
            clock_speed=SPEED,
            rpc_port=0,
            trace_enabled=True,
            trace_sample=1.0,
            trace_propagate=True,
            insight_history=True,
            insight_history_interval=1.0,
            insight_history_window=60.0,
            health_enabled=True,
            health_stall_warn_s=STALL_WARN_S,
            health_stall_crit_s=600.0,
            # cadence here is clock_speed-warped; this gate injects a
            # hard stall, the drift EWMA is covered by tests/test_health
            health_drift_factor=1e9,
            **kw,
        )

    leader = Node(obs_cfg(
        node_db_path=os.path.join(tmp, "leader-ns"),
        database_path=os.path.join(tmp, "leader.db"),
        validation_seed=val_key.human_seed,
        peer_port=leader_peer,
    )).setup().serve()

    followers: list = []
    leader_stopped = False
    try:
        master = leader.master_keys

        def payment(seq: int, dest: bytes) -> SerializedTransaction:
            tx = SerializedTransaction.build(
                TxType.ttPAYMENT, master.account_id, seq, 10,
                {sfAmount: STAmount.from_drops(250_000_000),
                 sfDestination: dest},
            )
            tx.sign(master)
            return tx

        dests = [KeyPair.from_passphrase(f"obsmoke-{i}").account_id
                 for i in range(8)]
        acked = threading.Semaphore(0)

        def cb(_tx, _ter, _applied):
            acked.release()

        def leader_validated():
            v = leader.ledger_master.validated
            return v.seq if v is not None else 0

        next_seq = 1
        for _ in range(20):
            leader.ops.submit_transaction(
                payment(next_seq, dests[next_seq % len(dests)]), cb)
            next_seq += 1
        for _ in range(20):
            acked.acquire()
        if not wait_until(lambda: leader_validated() >= 2, 90, 0.5):
            fail(f"leader never validated 2 ledgers solo "
                 f"(validated={leader_validated()})")

        for i, port in enumerate((f1_peer, f2_peer)):
            followers.append(Node(obs_cfg(
                node_mode="follower",
                node_db_path=os.path.join(tmp, f"f{i}-ns"),
                database_path=os.path.join(tmp, f"f{i}.db"),
                validators=[val_key.human_node_public],
                peer_port=port,
                ips=[f"127.0.0.1 {leader_peer}"],
            )).setup().serve())

        def fol_validated(n):
            v = n.ledger_master.validated
            return v.seq if v is not None else 0

        # flood WHILE the followers catch up and serve scrapes: the
        # relayed TxMessages carry the leader's trace context, so the
        # followers' ingest spans join the leader's trees
        stop_flood = threading.Event()

        def flood():
            nonlocal next_seq
            while not stop_flood.is_set():
                for _ in range(10):
                    leader.ops.submit_transaction(
                        payment(next_seq, dests[next_seq % len(dests)]),
                        cb,
                    )
                    next_seq += 1
                time.sleep(0.05)

        flooder = threading.Thread(target=flood, daemon=True)
        flooder.start()

        ok = wait_until(
            lambda: all(fol_validated(f) >= 3 for f in followers), 120, 0.5
        )
        if not ok:
            stop_flood.set()
            fail(f"followers never caught up "
                 f"(leader={leader_validated()}, "
                 f"followers={[fol_validated(f) for f in followers]})")

        # gate 1: /metrics scrapes clean MID-FLOOD on all three doors
        nodes = [("leader", leader)] + [
            (f"follower{i}", f) for i, f in enumerate(followers)
        ]
        for who, n in nodes:
            check_scrape(n.http_server.port, who)

        # gate 2: the metrics history ring sampled rows mid-flood
        hist = rpc(leader.http_server.port, "metrics_history", {"limit": 5})
        if not hist.get("enabled") or len(hist.get("series", [])) < 1:
            fail(f"metrics_history returned no rows: {hist}")

        # gate 3: clean-leg health is ok on every node (false-positive
        # guard — a healthy flood must not trip the watchdog)
        time.sleep(2.0)  # one more history/health cycle
        for who, n in nodes:
            hj = rpc(n.http_server.port, "health", {})
            if hj.get("health", {}).get("status") != "ok":
                fail(f"{who} health not ok on the clean leg: {hj}")

        stop_flood.set()
        flooder.join(timeout=5)

        # gate 4: merged cross-node trace — >=1 tx tree spanning all
        # three process lanes, single-rooted, every parent resolved
        dumps = [
            (who, fetch_dump(f"http://127.0.0.1:{n.http_server.port}"))
            for who, n in nodes
        ]
        merged = merge_dumps(dumps)
        problems = validate_chrome_trace(merged)
        problems += validate_merged_trace(merged, min_processes=3)
        if problems:
            for p in problems[:10]:
                print(f"  merged-trace problem: {p}", file=sys.stderr)
            fail(f"{len(problems)} merged-trace problems")
        wide = 0
        by_trace: dict[str, set] = {}
        for ev in merged["traceEvents"]:
            if ev.get("ph") == "M":
                continue
            tr = (ev.get("args") or {}).get("trace")
            if isinstance(tr, str) and len(tr) == 64:
                by_trace.setdefault(tr, set()).add(ev["pid"])
        wide = sum(1 for pids in by_trace.values() if len(pids) >= 3)
        out_path = os.path.join(tmp, "merged-trace.json")
        with open(out_path, "w", encoding="utf-8") as f:
            json.dump(merged, f)

        # gate 5: INJECTED cadence stall — kill the leader; both
        # followers stop seeing closes, the watchdog must flip to warn
        # within the sampling cadence and the flight recorder must ship
        leader_stopped = True
        leader.stop()

        def tripped():
            return all(
                f.health is not None and f.health.status != "ok"
                for f in followers
            )

        if not wait_until(tripped, STALL_WARN_S + 30, 0.5):
            fail(f"watchdog slept through an injected stall: "
                 f"{[f.health.get_json() for f in followers]}")
        # the status flips BEFORE the transition callback finishes its
        # fsync'd dump — give the watchdog thread a beat to land it
        wait_until(lambda: all(f.flight.dumps for f in followers), 30, 0.5)
        for i, f in enumerate(followers):
            reasons = f.health.get_json()["reasons"]
            if not any(r.startswith("close_stall") for r in reasons):
                fail(f"follower{i} tripped without a close_stall reason: "
                     f"{reasons}")
            if not f.flight.dumps:
                fail(f"follower{i} shipped no flight dump on degrade")
            if not os.path.exists(f.flight.dumps[-1]):
                fail(f"follower{i} flight dump missing on disk: "
                     f"{f.flight.dumps[-1]}")
            with open(f.flight.dumps[-1], encoding="utf-8") as fh:
                obj = json.load(fh)
            if not obj.get("health_transitions"):
                fail(f"follower{i} flight dump has no transitions")

        print(json.dumps({
            "observability_smoke": "ok",
            "validated_seq": min(fol_validated(f) for f in followers),
            "tx_traces_merged": len(by_trace),
            "tx_traces_spanning_3_processes": wide,
            "history_rows": len(hist.get("series", [])),
            "stall_tripped": [f.health.status for f in followers],
            "flight_dumps": [len(f.flight.dumps) for f in followers],
        }), flush=True)
    finally:
        for f in followers:
            f.stop()
        if not leader_stopped:
            leader.stop()
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    main()
