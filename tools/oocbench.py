#!/usr/bin/env python
"""ooc_state bench engine: one mode of the out-of-core comparison.

Builds (once, reusable via --dir) a segstore holding an N-account
ledger state tree, then replays a SEEDED flood-shaped write workload —
R closes of W account mutations each (80% against a small hot set,
20% uniform cold) — against the tree opened three ways:

  eager     all-in-RAM baseline: the whole tree deserialized up front
  uncapped  lazy faulting, effectively unbounded hot-node cache
  capped    lazy faulting, tiny [tree] cache_mb hot set

Per close it bulk-merges the write set, seals (hashes) the new root,
and flushes the delta back into the store — the state-plane half of a
ledger close. The workload is seeded, so the per-close ROOTS must be
byte-identical across all three modes (bench.py pins this every rep);
RSS and the hot-cache counters are the out-of-core evidence.

Emits ONE JSON line:
  {"mode", "accounts", "roots": [hex...], "close_ms": [...],
   "load_s", "rss_mb_loaded", "rss_mb_final", "cache": {...}}
"""

from __future__ import annotations

import argparse
import json
import os
import random
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

CACHE_CAPPED_MB = 64
CACHE_UNCAPPED_MB = 1 << 20  # 1 TB: never evicts


def rss_mb() -> float:
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return round(int(line.split()[1]) / 1024.0, 1)
    except OSError:
        pass
    return 0.0


def account_blob(i: int, balance: int, seq: int) -> tuple[bytes, bytes]:
    """(index, serialized account-root SLE) for synthetic account i —
    real STObject bytes, so leaf sizes and parse costs are honest."""
    import hashlib

    from stellard_tpu.protocol.formats import LedgerEntryType
    from stellard_tpu.protocol.sfields import (
        sfAccount, sfBalance, sfFlags, sfLedgerEntryType, sfOwnerCount,
        sfPreviousTxnID, sfPreviousTxnLgrSeq, sfSequence,
    )
    from stellard_tpu.protocol.stamount import STAmount
    from stellard_tpu.protocol.stobject import STObject
    from stellard_tpu.state import indexes

    account_id = hashlib.sha256(b"ooc-acct-%d" % i).digest()[:20]
    sle = STObject()
    sle[sfLedgerEntryType] = int(LedgerEntryType.ltACCOUNT_ROOT)
    sle[sfAccount] = account_id
    sle[sfBalance] = STAmount.from_drops(balance)
    sle[sfSequence] = seq
    sle[sfFlags] = 0
    sle[sfOwnerCount] = 0
    sle[sfPreviousTxnID] = b"\x00" * 32
    sle[sfPreviousTxnLgrSeq] = 0
    return indexes.account_root_index(account_id), sle.serialize()


def build_store(path: str, n_accounts: int, batch: int = 200_000) -> dict:
    """Build the N-account state tree and flush it into a segstore at
    `path`; returns (and writes) the meta {root, accounts}."""
    from stellard_tpu.nodestore.core import NodeObjectType, make_database
    from stellard_tpu.state.shamap import SHAMap, SHAMapItem

    t0 = time.time()
    db = make_database(type="segstore", path=path, durability="async",
                      async_writes=False)
    m = SHAMap()
    done = 0
    while done < n_accounts:
        hi = min(done + batch, n_accounts)
        items = [
            SHAMapItem(*account_blob(i, 1_000_000_000, 1))
            for i in range(done, hi)
        ]
        m.bulk_update(sets=items)
        done = hi
        print(f"oocbench: built {done}/{n_accounts} accounts "
              f"({time.time() - t0:.0f}s, rss {rss_mb()}MB)",
              file=sys.stderr, flush=True)
    root = m.get_hash()
    m.flush(
        db.store_fn(NodeObjectType.ACCOUNT_NODE), db.flushed,
        store_packed=db.store_packed_fn(NodeObjectType.ACCOUNT_NODE),
    )
    db.close()
    meta = {"root": root.hex(), "accounts": n_accounts}
    with open(os.path.join(path, "oocbench-meta.json"), "w") as f:
        json.dump(meta, f)
    print(f"oocbench: store built in {time.time() - t0:.0f}s",
          file=sys.stderr, flush=True)
    return meta


def run_mode(path: str, mode: str, closes: int, writes: int,
             seed: int, warmup: int = 3) -> dict:
    from stellard_tpu.nodestore.core import NodeObjectType, make_database
    from stellard_tpu.protocol.sfields import sfBalance, sfSequence
    from stellard_tpu.protocol.stamount import STAmount
    from stellard_tpu.protocol.stobject import STObject
    from stellard_tpu.state.shamap import (
        SHAMap, SHAMapItem, configure_inner_cache, inner_node_cache,
    )

    with open(os.path.join(path, "oocbench-meta.json")) as f:
        meta = json.load(f)
    root = bytes.fromhex(meta["root"])
    n_accounts = int(meta["accounts"])

    configure_inner_cache(
        CACHE_CAPPED_MB if mode == "capped" else CACHE_UNCAPPED_MB
    )
    cache = inner_node_cache()
    cache.clear()

    db = make_database(type="segstore", path=path, durability="async",
                      async_writes=False)

    fetched: set[bytes] = set()

    def fetch(h: bytes):
        o = db.fetch(h, populate_cache=False)
        if o is not None:
            fetched.add(h)
        return o.data if o else None

    t0 = time.time()
    if mode == "eager":
        m = SHAMap.from_store(root, fetch, use_cache=False)
        # the loaded tree is known-stored: per-close flushes write only
        # the delta (Ledger.load's known-set contract)
        db.flushed.update(fetched)
    else:
        m = SHAMap.from_store(root, fetch, lazy=True,
                              store_known=db.flushed)
    load_s = time.time() - t0
    loaded_rss = rss_mb()

    # flood-shaped write sets: 80% of mutations hit a 10k-account hot
    # set, 20% the uniform cold tail — seeded, so every mode replays the
    # identical sequence and the per-close roots must match
    rng = random.Random(seed)
    hot = max(1, min(10_000, n_accounts // 10))
    # warm the declared hot set in EVERY mode before timing: "the hot
    # set stays resident" is the operator's contract ([tree] cache_mb
    # is sized for it) — the eager mode pre-pays this inside its
    # O(state) load, the lazy modes pay exactly the hot set here. The
    # steady-state closes then measure the real out-of-core tax: the
    # uniform cold tail, which NO cache can keep resident.
    t0 = time.time()
    for i in range(hot):
        m.get(account_blob(i, 0, 0)[0])
    warm_s = round(time.time() - t0, 2)
    # warmup closes populate the lazy modes' hot set the way the eager
    # mode's O(state) load phase pre-pays it — the reported close_ms
    # are steady-state; the per-close ROOTS include warmup closes, so
    # byte-identity is pinned over every rep regardless
    close_ms: list[float] = []
    roots: list[str] = []
    for r in range(warmup + closes):
        t0 = time.time()
        sets = []
        touched: set[bytes] = set()
        for _ in range(writes):
            if rng.random() < 0.8:
                i = rng.randrange(hot)
            else:
                i = rng.randrange(n_accounts)
            idx, _ = account_blob(i, 0, 0)
            if idx in touched:
                continue
            touched.add(idx)
            item = m.get(idx)
            if item is None:
                continue
            sle = STObject.from_bytes(item.data)
            bal = sle[sfBalance].mantissa - (r + 1)
            sle[sfBalance] = STAmount.from_drops(max(0, bal))
            sle[sfSequence] = int(sle[sfSequence]) + 1
            sets.append(SHAMapItem(idx, sle.serialize()))
        m.bulk_update(sets=sets)
        h = m.get_hash()
        m.flush(
            db.store_fn(NodeObjectType.ACCOUNT_NODE), db.flushed,
            store_packed=db.store_packed_fn(NodeObjectType.ACCOUNT_NODE),
        )
        if r >= warmup:
            close_ms.append(round((time.time() - t0) * 1000.0, 2))
        roots.append(h.hex())

    out = {
        "mode": mode,
        "accounts": n_accounts,
        "roots": roots,
        "close_ms": close_ms,
        "load_s": round(load_s, 2),
        "warm_s": warm_s,
        "rss_mb_loaded": loaded_rss,
        "rss_mb_final": rss_mb(),
        "cache": cache.get_json(),
    }
    db.close()
    return out


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--dir", required=True)
    ap.add_argument("--accounts", type=int, default=5_000_000)
    ap.add_argument("--mode", choices=("eager", "uncapped", "capped"),
                    default=None)
    ap.add_argument("--closes", type=int, default=20)
    ap.add_argument("--writes", type=int, default=200)
    ap.add_argument("--seed", type=int, default=42)
    ap.add_argument("--warmup", type=int, default=3)
    ap.add_argument("--build-only", action="store_true")
    args = ap.parse_args()

    meta_path = os.path.join(args.dir, "oocbench-meta.json")
    if not os.path.exists(meta_path):
        build_store(args.dir, args.accounts)
    if args.build_only:
        print(json.dumps({"built": True}), flush=True)
        return 0
    if args.mode is None:
        print("oocbench: --mode required after build", file=sys.stderr)
        return 2
    out = run_mode(args.dir, args.mode, args.closes, args.writes,
                   args.seed, warmup=args.warmup)
    print(json.dumps(out), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
