#!/usr/bin/env python3
"""Out-of-core state-plane smoke gate (tools/tier1.sh).

End to end, on the REAL node stack:

1. ``base`` phase: a fresh standalone file-backed node floods 100 txs
   (4 closes) through the full async pipeline and stops — a persisted
   chain on disk.
2. The state dir is copied twice and resumed (``start_up=load``, which
   opens the trees LAZILY) with online deletion + history shards on,
   under two ``[tree] cache_mb`` budgets: deliberately tiny (capped)
   and effectively unbounded (uncapped). Each resume floods 200 more
   txs (8 closes).
3. The gate asserts:
   - per-seq state/tx ROOTS byte-identical between capped and
     uncapped (lazy faulting under eviction pressure changes nothing);
   - the capped run actually FAULTED (nonzero
     shamap_inner_cache.faults — anti-vacuity: a smoke that never
     exercised the out-of-core path proves nothing);
   - capped-run RSS growth during the flood stays bounded;
   - online deletion rotated with a shard SEAL, and an account_tx for
     a window BELOW the sql_trim retain floor is served from a shard
     (rows carry shard provenance) instead of lgrIdxInvalid.

Exit 0 on pass; 1 with the failures listed otherwise.
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

BASE_CLOSES = 4
RUN_CLOSES = 8
TXS_PER_CLOSE = 25
CAPPED_MB = 2
UNCAPPED_MB = 4096
RSS_DELTA_CAP_MB = 400.0  # loose sanity bound for a 200-tx smoke


def rss_mb() -> float:
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return round(int(line.split()[1]) / 1024.0, 1)
    except OSError:
        pass
    return 0.0


def _mk_node(state_dir: str, *, load: bool, cache_mb: int,
             rotate: bool):
    from stellard_tpu.node.config import Config
    from stellard_tpu.node.node import Node

    cfg = Config(
        node_db_type="segstore",
        node_db_path=os.path.join(state_dir, "nodestore"),
        database_path=os.path.join(state_dir, "stellard.db"),
        node_db_segment_mb=1,
        tree_cache_mb=cache_mb,
    )
    if load:
        cfg.start_up = "load"
    if rotate:
        cfg.node_db_online_delete = 4
        cfg.node_db_online_delete_interval = 2
        cfg.node_db_shards = "1"
    return Node(cfg).setup()


def _flood(node, closes: int, start_seq: int) -> tuple[list[dict], int]:
    import threading

    from stellard_tpu.protocol.formats import TxType
    from stellard_tpu.protocol.keys import KeyPair
    from stellard_tpu.protocol.sfields import sfAmount, sfDestination
    from stellard_tpu.protocol.stamount import STAmount
    from stellard_tpu.protocol.sttx import SerializedTransaction

    master = KeyPair.from_passphrase("masterpassphrase")
    dests = [KeyPair.from_passphrase(f"ooc-smoke-{i}").account_id
             for i in range(8)]
    done = threading.Semaphore(0)

    def cb(tx, ter, applied):
        done.release()

    roots = []
    seq = start_seq
    for _ in range(closes):
        txs = []
        for i in range(TXS_PER_CLOSE):
            tx = SerializedTransaction.build(
                TxType.ttPAYMENT, master.account_id, seq, 10,
                {sfAmount: STAmount.from_drops(1_000_000),
                 sfDestination: dests[i % len(dests)]},
            )
            tx.sign(master)
            txs.append(tx)
            seq += 1
        for tx in txs:
            node.ops.submit_transaction(tx, cb)
        for _ in txs:
            done.acquire()
        closed, _results = node.ops.accept_ledger()
        roots.append({
            "seq": closed.seq,
            "account_hash": closed.account_hash.hex(),
            "tx_hash": closed.tx_hash.hex(),
        })
    node.close_pipeline.flush(timeout=120)
    return roots, seq


def phase_base(state_dir: str) -> None:
    node = _mk_node(state_dir, load=False, cache_mb=UNCAPPED_MB,
                    rotate=False)
    try:
        _roots, seq = _flood(node, BASE_CLOSES, 1)
        print(json.dumps({"phase": "base", "next_seq": seq}), flush=True)
    finally:
        node.stop()


def phase_run(state_dir: str, cache_mb: int, start_seq: int) -> None:
    import time

    from stellard_tpu.rpc.handlers import Context, Role, dispatch

    rss0 = rss_mb()
    node = _mk_node(state_dir, load=True, cache_mb=cache_mb, rotate=True)
    try:
        roots, _seq = _flood(node, RUN_CLOSES, start_seq)
        rss1 = rss_mb()
        # a rotation (sweep + shard seal + sql trim) must have landed:
        # drive extra empty closes until the deleter reports one
        deadline = time.time() + 60
        while time.time() < deadline:
            dj = node.online_deleter.get_json()
            floor = node.txdb.retain_floor
            if dj["sweeps_completed"] >= 1 and dj["shards_sealed"] >= 1 \
                    and floor > 1:
                break
            node.ops.accept_ledger()
            node.close_pipeline.flush(timeout=60)
            time.sleep(0.1)
        deleter = node.online_deleter.get_json()
        floor = node.txdb.retain_floor
        shard_rows = []
        shard_error = ""
        if floor > 1:
            try:
                out = dispatch(
                    Context(node, {
                        "account": _master_address(),
                        "ledger_index_min": 1,
                        "ledger_index_max": floor - 1,
                        "limit": 5,
                    }, Role.ADMIN),
                    "account_tx",
                )
                shard_rows = [
                    t for t in out.get("transactions", [])
                    if "shard" in t
                ]
            except Exception as e:  # noqa: BLE001 — reported, judged by parent
                shard_error = repr(e)[:200]
        counters = dispatch(Context(node, {}, Role.ADMIN), "get_counts")
        print(json.dumps({
            "phase": "run",
            "cache_mb": cache_mb,
            "roots": roots,
            "rss_mb_before": rss0,
            "rss_mb_after": rss1,
            "inner_cache": counters["shamap_inner_cache"],
            "history_shards": counters.get("history_shards"),
            "online_delete": deleter,
            "retain_floor": floor,
            "shard_rows": len(shard_rows),
            "shard_error": shard_error,
        }), flush=True)
    finally:
        node.stop()


def _master_address() -> str:
    from stellard_tpu.protocol.keys import KeyPair

    return KeyPair.from_passphrase("masterpassphrase").human_account_id


def _spawn(args: list[str]) -> dict:
    r = subprocess.run(
        [sys.executable, os.path.abspath(__file__), *args],
        capture_output=True, text=True, timeout=600,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    if r.returncode != 0:
        print(r.stdout[-2000:], file=sys.stderr)
        print(r.stderr[-2000:], file=sys.stderr)
        raise RuntimeError(f"phase {args} failed rc={r.returncode}")
    return json.loads(r.stdout.strip().splitlines()[-1])


def run_smoke() -> int:
    top = tempfile.mkdtemp(prefix="oocsmoke-")
    failures: list[str] = []
    try:
        base = os.path.join(top, "base")
        os.makedirs(base)
        b = _spawn(["--phase", "base", "--dir", base])
        next_seq = int(b["next_seq"])
        runs = {}
        for name, mb in (("capped", CAPPED_MB), ("uncapped", UNCAPPED_MB)):
            d = os.path.join(top, name)
            shutil.copytree(base, d)
            runs[name] = _spawn([
                "--phase", "run", "--dir", d, "--cache-mb", str(mb),
                "--start-seq", str(next_seq),
            ])
        cap, unc = runs["capped"], runs["uncapped"]
        if cap["roots"] != unc["roots"]:
            failures.append(
                f"ROOTS DIVERGED between capped and uncapped runs: "
                f"{cap['roots'][:2]} vs {unc['roots'][:2]}"
            )
        faults = cap["inner_cache"]["faults"]
        if faults <= 0:
            failures.append(
                "anti-vacuity: capped run recorded ZERO faults — the "
                "out-of-core path never ran"
            )
        delta = cap["rss_mb_after"] - cap["rss_mb_before"]
        if delta > RSS_DELTA_CAP_MB:
            failures.append(
                f"capped-run RSS grew {delta:.0f}MB during a 200-tx "
                f"flood (bound {RSS_DELTA_CAP_MB}MB)"
            )
        if cap["retain_floor"] <= 1:
            failures.append(
                f"online deletion never trimmed (floor="
                f"{cap['retain_floor']}) — the shard leg is vacuous"
            )
        od = cap["online_delete"]
        if od.get("shards_sealed", 0) < 1:
            failures.append(f"no shard sealed: online_delete={od}")
        if cap["shard_rows"] < 1:
            failures.append(
                f"below-floor account_tx served NO shard rows "
                f"(floor={cap['retain_floor']}, "
                f"err={cap['shard_error']!r}, "
                f"shards={cap['history_shards']})"
            )
        print(
            f"ooc smoke: roots_identical={cap['roots'] == unc['roots']} "
            f"faults={faults} rss_delta={delta:.0f}MB "
            f"floor={cap['retain_floor']} "
            f"shard_rows={cap['shard_rows']} "
            f"sealed={od.get('shards_sealed')}"
        )
        for f in failures:
            print(f"ooc smoke FAIL: {f}", file=sys.stderr)
        return 1 if failures else 0
    finally:
        shutil.rmtree(top, ignore_errors=True)


def main() -> int:
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--phase", choices=("base", "run"), default=None)
    ap.add_argument("--dir", default="")
    ap.add_argument("--cache-mb", type=int, default=UNCAPPED_MB)
    ap.add_argument("--start-seq", type=int, default=1)
    args = ap.parse_args()
    if args.phase == "base":
        phase_base(args.dir)
        return 0
    if args.phase == "run":
        phase_run(args.dir, args.cache_mb, args.start_seq)
        return 0
    return run_smoke()


if __name__ == "__main__":
    sys.exit(main())
