#!/usr/bin/env python3
"""Overload-admission smoke gate (tier-1).

Boots a standalone node with a small pinned admission cap, floods it at
~4x that capacity through the full async pipeline, and fails loudly
unless the admission-control plane ([txq], node/txq.py) holds the line:

- the RPC door stays responsive DURING the flood (server_state / fee
  round-trips over the real HTTP door under a hard latency bound),
- no closed ledger ever exceeds the soft cap,
- the queue drains in fee order (higher-fee senders validate no later
  than lower-fee senders),
- the legacy held pile does not grow (queued holds are fee-ordered, not
  an unbounded side dict),
- the queue itself stays within its configured bound.

Run: JAX_PLATFORMS=cpu python tools/overload_smoke.py
"""

from __future__ import annotations

import json
import sys
import threading
import time
import urllib.request

import os

# runnable as "python tools/overload_smoke.py" from anywhere: a script in
# tools/ does not get the repo root on sys.path by itself
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

CAP = 16          # pinned soft cap (min_cap == max_cap)
SENDERS = 16      # one fee tier per sender
ROUNDS = 4        # rounds of 4x-cap floods
XRP = 1_000_000


def rpc(url: str, method: str, params: dict | None = None) -> dict:
    req = urllib.request.Request(
        url,
        data=json.dumps(
            {"method": method, "params": [params or {}]}
        ).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=10) as resp:
        return json.loads(resp.read())


def main() -> int:
    from stellard_tpu.node.config import Config
    from stellard_tpu.node.node import Node
    from stellard_tpu.protocol.formats import TxType
    from stellard_tpu.protocol.keys import KeyPair
    from stellard_tpu.protocol.sfields import sfAmount, sfDestination
    from stellard_tpu.protocol.stamount import STAmount
    from stellard_tpu.protocol.sttx import SerializedTransaction

    node = Node(Config(
        rpc_port=0,
        txq_min_cap=CAP, txq_max_cap=CAP,
        txq_ledgers_in_queue=8, txq_account_cap=8,
    )).setup().serve()
    failures: list[str] = []
    try:
        url = f"http://127.0.0.1:{node.http_server.port}"
        master = KeyPair.from_passphrase("masterpassphrase")
        senders = [KeyPair.from_passphrase(f"ov-smoke-{i}")
                   for i in range(SENDERS)]
        dests = [KeyPair.from_passphrase(f"ov-smoke-dest-{i}").account_id
                 for i in range(SENDERS)]

        def payment(kp, seq, dest, drops, fee):
            tx = SerializedTransaction.build(
                TxType.ttPAYMENT, kp.account_id, seq, fee,
                {sfAmount: STAmount.from_drops(drops),
                 sfDestination: dest},
            )
            tx.sign(kp)
            return tx

        done = threading.Semaphore(0)

        def cb(tx, ter, applied):
            done.release()

        # fund the senders (escalation-proof fee: funding never queues)
        for i, s in enumerate(senders):
            node.ops.submit_transaction(
                payment(master, i + 1, s.account_id, 2_000 * XRP,
                        fee=10_000_000), cb,
            )
        for _ in senders:
            done.acquire()
        node.ops.accept_ledger()

        # flood at 4x the cap: each round submits 4*CAP txs, one fee
        # tier per sender (fee 10+i), disjoint destinations
        submitted: dict[bytes, int] = {}  # txid -> sender index
        rpc_ms: list[float] = []
        sizes = []  # every close's size — flood rounds AND drain
        for rnd in range(ROUNDS):
            for k in range(4):
                for i, s in enumerate(senders):
                    seq = rnd * 4 + k + 1
                    tx = payment(s, seq, dests[i], 250 * XRP, fee=10 + i)
                    submitted[tx.txid()] = i
                    node.ops.submit_transaction(tx, cb)
            for _ in range(4 * SENDERS):
                done.acquire()
            # RPC responsiveness DURING the flood
            for method in ("server_state", "fee"):
                t0 = time.perf_counter()
                out = rpc(url, method)
                dt = (time.perf_counter() - t0) * 1000.0
                rpc_ms.append(dt)
                if "error" in str(out)[:200].lower() and "result" not in out:
                    failures.append(f"RPC {method} errored mid-flood: {out}")
            closed, _res = node.ops.accept_ledger()
            # the cap must hold in the very rounds we flood, not just
            # the easy post-flood drain regime below
            sizes.append(len(list(closed.tx_entries())))
            if len(node.ledger_master.held) != 0:
                failures.append(
                    f"held pile grew to {len(node.ledger_master.held)} "
                    f"in round {rnd} — holds must ride the queue"
                )
            if len(node.txq) > node.txq.max_size:
                failures.append(
                    f"queue exceeded its bound: {len(node.txq)} > "
                    f"{node.txq.max_size}"
                )

        if max(rpc_ms) > 2000.0:
            failures.append(
                f"RPC latency collapsed under flood: max {max(rpc_ms):.0f} ms"
            )

        # drain: close until the queue is empty (bounded by retention);
        # quiesce models the inter-close open window so the deferred
        # promotion lands between closes
        landed: dict[bytes, int] = {}  # txid -> ledger seq
        for _ in range(24):
            node.txq.quiesce()
            closed, results = node.ops.accept_ledger()
            sizes.append(len(list(closed.tx_entries())))
            for txid in results:
                if txid in submitted:
                    landed[txid] = closed.seq
            if len(node.txq) == 0:
                break
        if len(node.txq) != 0:
            failures.append(f"queue failed to drain: {len(node.txq)} left")
        if max(sizes) > CAP:
            failures.append(
                f"a closed ledger exceeded the soft cap: {max(sizes)} > {CAP}"
            )

        # fee-order drain: a sender's LAST tx to land is its drain
        # completion; higher-fee senders must complete no later than
        # lower-fee senders among fully-landed tiers
        last_by_sender: dict[int, int] = {}
        for txid, i in submitted.items():
            if txid in landed:
                last_by_sender[i] = max(
                    last_by_sender.get(i, 0), landed[txid]
                )
        tiers = sorted(last_by_sender)  # sender idx == fee order
        for lo, hi in zip(tiers, tiers[1:]):
            if last_by_sender[hi] > last_by_sender[lo]:
                failures.append(
                    f"fee-order violation: sender {hi} (fee {10 + hi}) "
                    f"drained at seq {last_by_sender[hi]} AFTER sender "
                    f"{lo} (fee {10 + lo}) at {last_by_sender[lo]}"
                )

        j = node.txq.get_json()
        if j["promoted"] == 0:
            failures.append("promotion never ran — queue is a black hole")
    finally:
        node.stop()

    if failures:
        print("overload smoke FAILED:", file=sys.stderr)
        for f in failures:
            print(f"  - {f}", file=sys.stderr)
        return 1
    print(
        f"overload smoke OK: {ROUNDS * 4 * SENDERS} txs at 4x cap {CAP}, "
        f"max close size {max(sizes)}, queue drained in fee order, "
        f"max mid-flood RPC {max(rpc_ms):.0f} ms, "
        f"promoted {j['promoted']} evicted {j['evicted']} "
        f"spliced {j['promote_spliced']}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
