"""Liquidity-plane bench: the paths read plane under a crossfire flood.

Run as a SUBPROCESS of bench.py's bench_path_plane() (the virtual
device-count flag must be set before backend init). Prints one JSON
line; the wrapper turns it into BENCH metric lines with honest
fallback/provenance fields.

Two measured parts, mirroring the ISSUE 17 acceptance criteria:

1. Node episodes, interleaved best-of-K: a FILE-BACKED standalone node
   floods an order-book crossfire (offer creates, tier-consuming
   crossings, cancels) over a ledger seeded with MANY idle books, with
   and without live path_find subscriptions. Per mode the best rep's
   close p50 is kept (PERF.md's best-of convention — this box's CPU
   allotment fluctuates between runs). Criteria:
     (a) book re-reads per close << total books (the incremental index
         only re-scans books the close's write set touched, never the
         whole book plane) — counter-pinned from LiveBookIndex;
     (b) p99 subscription staleness (ledgers) recorded from the
         plane's histogram, under a deliberately tight per-close
         budget (budget < subs, so shedding + stalest-first engage);
     (c) subscribed close p50 within 10% of the no-subscription
         baseline — pathfinding never serializes into the close (the
         publisher runs off-close; what the close path gains is ONLY
         the incremental index advance).

2. Device identity sweep: host arm vs forced-device arm of the routed
   PathQualityEvaluator over seeded Q16.16 rate matrices at mesh
   widths 1/2/4/8 — byte identity at every width, every batch shape
   (d). On this box the mesh is virtual CPU shards and the output says
   so (platform + virtual_devices fields; a CPU-emulated sweep must
   never masquerade as a chip number).
"""

from __future__ import annotations

import json
import os
import re
import statistics
import sys
import threading
import time

N_DEVICES = int(os.environ.get("PATH_BENCH_DEVICES", "8"))
WIDTHS = [int(w) for w in
          os.environ.get("PATH_BENCH_WIDTHS", "1,2,4,8").split(",")]
N_CLOSES = int(os.environ.get("BENCH_PATH_CLOSES", "10"))
N_SUBS = int(os.environ.get("BENCH_PATH_SUBS", "8"))
REPS = max(1, int(os.environ.get("BENCH_PATH_REPS", "3")))
N_IDLE_BOOKS = int(os.environ.get("BENCH_PATH_IDLE_BOOKS", "12"))

opt = f"--xla_force_host_platform_device_count={N_DEVICES}"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" in flags:
    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", opt, flags)
else:
    flags = (flags + " " + opt).strip()
os.environ["XLA_FLAGS"] = flags
os.environ["JAX_PLATFORMS"] = "cpu"

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run_episode(subscribed: bool, state_dir: str) -> dict:
    """One file-backed node lifetime: seed accounts + idle books, then
    N_CLOSES measured crossfire closes (with live subscriptions and a
    tight update budget when ``subscribed``)."""
    from stellard_tpu.node.config import Config
    from stellard_tpu.node.node import Node
    from stellard_tpu.protocol.formats import TxType
    from stellard_tpu.protocol.keys import KeyPair
    from stellard_tpu.protocol.sfields import (
        sfAmount,
        sfDestination,
        sfLimitAmount,
        sfOfferSequence,
        sfTakerGets,
        sfTakerPays,
    )
    from stellard_tpu.protocol.stamount import STAmount, currency_from_iso
    from stellard_tpu.protocol.sttx import SerializedTransaction
    from stellard_tpu.rpc.infosub import InfoSub, SubscriptionManager

    USD = currency_from_iso("USD")
    M = 1_000_000

    node = Node(Config(
        signature_backend="cpu",
        database_path=os.path.join(state_dir, "bench.db"),
        node_db_type=os.environ.get("BENCH_NODE_DB", "segstore"),
        node_db_durability=os.environ.get(
            "BENCH_NODE_DB_DURABILITY", "batch"),
        node_db_path=os.path.join(state_dir, "nodestore"),
    )).setup()
    try:
        plane = node.path_plane
        assert plane is not None, "[paths] plane is not wired"

        master = KeyPair.from_passphrase("masterpassphrase")
        gw = KeyPair.from_passphrase("path-bench-gw")
        traders = [KeyPair.from_passphrase(f"path-bench-t{i}")
                   for i in range(4)]
        seqs: dict[bytes, int] = {master.account_id: 1}
        done = threading.Semaphore(0)

        def iou(v, cur=USD):
            return STAmount.from_iou(cur, gw.account_id, v, 0)

        def drops(v):
            return STAmount.from_drops(v)

        def tx_of(key, tx_type, fields):
            s = seqs.setdefault(key.account_id, 1)
            tx = SerializedTransaction.build(
                tx_type, key.account_id, s, 10, fields)
            tx.sign(key)
            seqs[key.account_id] = s + 1
            return tx

        def submit_all(txs):
            for tx in txs:
                node.ops.submit_transaction(tx, lambda *_: done.release())
            for _ in txs:
                done.acquire()

        def close():
            t0 = time.perf_counter()
            closed, _results = node.ops.accept_ledger()
            return closed, time.perf_counter() - t0

        # -- setup closes (untimed): accounts, trust, float, idle books
        submit_all([
            tx_of(master, TxType.ttPAYMENT,
                  {sfAmount: drops(2_000 * M), sfDestination: k.account_id})
            for k in [gw, *traders]
        ])
        close()
        submit_all([
            tx_of(t, TxType.ttTRUST_SET,
                  {sfLimitAmount: STAmount.from_iou(
                      USD, gw.account_id, 1_000_000, 0)})
            for t in traders
        ])
        close()
        # the idle book plane: the gateway quotes N distinct IOU/XRP
        # pairs the crossfire never touches — criterion (a) is that the
        # incremental index re-reads the 1-3 books each close writes,
        # NOT this whole plane
        submit_all([
            tx_of(gw, TxType.ttPAYMENT,
                  {sfAmount: iou(10_000), sfDestination: t.account_id})
            for t in traders
        ] + [
            tx_of(gw, TxType.ttOFFER_CREATE,
                  {sfTakerPays: drops((50 + b) * M),
                   sfTakerGets: iou(50, currency_from_iso(f"C{b:02d}"))})
            for b in range(N_IDLE_BOOKS)
        ])
        close()

        live_offers: list[tuple] = []
        rnd_rate = [1, 2, 3]

        def crossfire(i):
            txs = []
            a, b, c = (traders[i % 4], traders[(i + 1) % 4],
                       traders[(i + 2) % 4])
            rate = rnd_rate[i % 3]
            live_offers.append((a, seqs.setdefault(a.account_id, 1)))
            txs.append(tx_of(a, TxType.ttOFFER_CREATE,
                             {sfTakerPays: drops(10 * rate * M),
                              sfTakerGets: iou(10)}))
            if i % 2 == 0:
                txs.append(tx_of(b, TxType.ttOFFER_CREATE,
                                 {sfTakerPays: iou(5),
                                  sfTakerGets: drops(5 * 3 * M)}))
            if i % 3 == 2 and live_offers:
                owner, oseq = live_offers.pop(0)
                txs.append(tx_of(owner, TxType.ttOFFER_CANCEL,
                                 {sfOfferSequence: oseq}))
            if i % 4 == 3:
                txs.append(tx_of(c, TxType.ttOFFER_CREATE,
                                 {sfTakerPays: iou(20),
                                  sfTakerGets: drops(10 * M)}))
            return txs

        mgr = None
        boxes: list[list] = []
        budget = max(1, N_SUBS // 2)
        if subscribed:
            # deliberately tight budget: budget < subs forces shedding
            # + stalest-first rotation, so the staleness histogram the
            # bench reports is exercised, not vacuously zero
            plane.max_updates_per_close = budget
            mgr = SubscriptionManager(node.ops)  # node.subs waits for serve()
            # drive the publisher synchronously below (normally a
            # jtUPDATE_PF job) so deliveries are deterministic; the
            # close timing never includes it either way — that is the
            # design under test, and note_close (the index advance) is
            # the only paths work left ON the close path
            node.ops.on_ledger_closed.remove(mgr._pub_ledger)
            mgr.path_plane = plane
            boxes = [[] for _ in range(N_SUBS)]
            for j, box in enumerate(boxes):
                mgr.create_path_request(InfoSub(box.append), {
                    "src": traders[j % 4].account_id,
                    "dst": traders[(j + 1) % 4].account_id,
                    "dst_amount": iou(5),
                })

        rereads0 = plane.index.counters()["book_rereads"]
        times = []
        closed = None
        for i in range(N_CLOSES):
            submit_all(crossfire(i))
            closed, dt = close()
            times.append(dt)
            if mgr is not None:
                mgr._pub_path_updates(closed)

        counters = plane.index.counters()
        total_books = len(plane.books_for(closed).books)
        out = {
            "close_p50_ms": statistics.median(times) * 1000.0,
            "closes": N_CLOSES,
            "book_rereads": counters["book_rereads"] - rereads0,
            "total_books": total_books,
            "index": counters,
        }
        if subscribed:
            out["subs"] = {
                "n_subs": N_SUBS,
                "budget": budget,
                "delivered": sum(len(b) for b in boxes),
                "reranked": plane.reranked,
                "shed_budget": plane.shed_budget,
                "staleness_p99": plane.staleness_quantile(0.99),
                "staleness_max": plane.staleness_max,
            }
        return out
    finally:
        node.stop()


def device_identity_sweep() -> dict:
    """Host arm vs forced-device arm byte identity at every mesh width,
    over seeded Q16.16 rate matrices at several batch shapes."""
    import jax
    import numpy as np

    from stellard_tpu.crypto.backend import make_path_evaluator

    devices = jax.devices()
    platform = devices[0].platform
    rng = np.random.default_rng(17)
    batches = [(1, 8), (37, 8), (128, 6), (512, 8)]
    mats = [rng.integers(0, 1 << 32, size=shape, dtype=np.uint32)
            for shape in batches]

    host = make_path_evaluator(routing="host")
    refs = [host.evaluate(m) for m in mats]

    per_width = {}
    all_identical = True
    for w in WIDTHS:
        ev = make_path_evaluator(mesh=str(w), routing="device")
        t0 = time.perf_counter()
        outs = [ev.evaluate(m) for m in mats]
        dt = time.perf_counter() - t0
        identical = all(
            o.tobytes() == r.tobytes() for o, r in zip(outs, refs))
        all_identical = all_identical and identical
        widths = ev.get_json()["arm_widths"]
        per_width[str(w)] = {
            "identical": identical,
            "arm_width": max(widths.values()),
            "rows_per_sec": round(
                sum(m.shape[0] for m in mats) / max(dt, 1e-9), 1),
        }
    return {
        "widths": WIDTHS,
        "identical_every_width": all_identical,
        "per_width": per_width,
        "batches": [list(s) for s in batches],
        "platform": platform,
        "virtual_devices": len(devices) if platform == "cpu" else None,
    }


def main() -> int:
    import shutil
    import tempfile

    # interleaved best-of-K pairs (PERF.md's best-of convention): the
    # box's CPU allotment fluctuates between otherwise-identical runs,
    # so a single A/B pair routinely inverts
    legs = {"nosub": [], "subs": []}
    for _rep in range(REPS):
        for mode, subscribed in (("nosub", False), ("subs", True)):
            state_dir = tempfile.mkdtemp(prefix=f"bench-paths-{mode}-")
            try:
                legs[mode].append(run_episode(subscribed, state_dir))
            finally:
                shutil.rmtree(state_dir, ignore_errors=True)

    best = {m: min(runs, key=lambda r: r["close_p50_ms"])
            for m, runs in legs.items()}
    device = device_identity_sweep()

    print(json.dumps({
        "reps": REPS,
        "nosub_close_p50_ms": round(best["nosub"]["close_p50_ms"], 3),
        "subs_close_p50_ms": round(best["subs"]["close_p50_ms"], 3),
        "nosub_p50s_ms": [round(r["close_p50_ms"], 3)
                          for r in legs["nosub"]],
        "subs_p50s_ms": [round(r["close_p50_ms"], 3)
                         for r in legs["subs"]],
        "book_rereads": best["subs"]["book_rereads"],
        "closes": best["subs"]["closes"],
        "total_books": best["subs"]["total_books"],
        "index": best["subs"]["index"],
        "subs": best["subs"]["subs"],
        "device": device,
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
