#!/usr/bin/env python3
"""Liquidity-plane smoke gate (tools/tier1.sh, ISSUE 17).

Boots a standalone node (paths plane on by default), floods an
order-book crossfire through the full async pipeline — offer creation,
partial-fill tier consumption, full crossings that empty books, and
cancels — while N live path_find subscriptions (plus one resource-
throttled path-spam flooder) ride the per-close publisher. Gates:

1. identity per close: the incrementally-advanced book index equals a
   from-scratch full state scan after EVERY close (and the incremental
   path actually engaged — anti-vacuity via the index counters);
2. re-ranked deliveries: every close with live subscriptions delivers
   path_find updates (the plane's claim/rank path, not a silent skip);
3. close cadence: the p50 close wall time during the subscribed flood
   stays within tolerance of the pre-subscription baseline closes —
   pathfinding must never serialize into the close;
4. shedding: the flooder's throttled endpoint is SHED by the resource
   plane while polite subscribers keep their deliveries.

Exit 0 when every gate holds; 1 otherwise.
"""

from __future__ import annotations

import os
import statistics
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run_smoke(n_closes: int = 12, n_subs: int = 4) -> int:
    from stellard_tpu.node.config import Config
    from stellard_tpu.node.node import Node
    from stellard_tpu.overlay.resource import FEE_PATH_FIND, ResourceManager
    from stellard_tpu.paths import OrderBookDB
    from stellard_tpu.protocol.formats import TxType
    from stellard_tpu.protocol.keys import KeyPair
    from stellard_tpu.protocol.sfields import (
        sfAmount,
        sfDestination,
        sfLimitAmount,
        sfOfferSequence,
        sfTakerGets,
        sfTakerPays,
    )
    from stellard_tpu.protocol.stamount import STAmount, currency_from_iso
    from stellard_tpu.protocol.sttx import SerializedTransaction
    from stellard_tpu.rpc.infosub import InfoSub

    USD = currency_from_iso("USD")
    M = 1_000_000

    node = Node(Config(signature_backend="cpu")).setup()
    bad = []
    try:
        plane = node.path_plane
        if plane is None:
            print("path smoke: [paths] plane is not wired", file=sys.stderr)
            return 1
        if node.rpc_resources is None:
            node.rpc_resources = ResourceManager()
        plane.resources = node.rpc_resources

        master = KeyPair.from_passphrase("masterpassphrase")
        gw = KeyPair.from_passphrase("path-smoke-gw")
        traders = [KeyPair.from_passphrase(f"path-smoke-t{i}")
                   for i in range(4)]
        seqs: dict[bytes, int] = {master.account_id: 1}
        done = threading.Semaphore(0)

        def iou(v):
            return STAmount.from_iou(USD, gw.account_id, v, 0)

        def drops(v):
            return STAmount.from_drops(v)

        def tx_of(key, tx_type, fields):
            s = seqs.setdefault(key.account_id, 1)
            tx = SerializedTransaction.build(
                tx_type, key.account_id, s, 10, fields)
            tx.sign(key)
            seqs[key.account_id] = s + 1
            return tx

        def submit_all(txs):
            for tx in txs:
                node.ops.submit_transaction(tx, lambda *_: done.release())
            for _ in txs:
                done.acquire()

        close_times: list[float] = []

        def close():
            t0 = time.perf_counter()
            closed, _results = node.ops.accept_ledger()
            close_times.append(time.perf_counter() - t0)
            return closed

        def check_identity(closed):
            inc = plane.books_for(closed).books
            full = OrderBookDB().setup(closed).books
            if inc != full:
                bad.append(
                    f"seq {closed.seq}: incremental {len(inc)} books != "
                    f"full scan {len(full)}")

        # -- seed accounts, trust lines, IOU float ------------------------
        submit_all([
            tx_of(master, TxType.ttPAYMENT,
                  {sfAmount: drops(2_000 * M), sfDestination: k.account_id})
            for k in [gw, *traders]
        ])
        check_identity(close())
        submit_all([
            tx_of(t, TxType.ttTRUST_SET,
                  {sfLimitAmount: STAmount.from_iou(
                      USD, gw.account_id, 1_000_000, 0)})
            for t in traders
        ])
        check_identity(close())
        submit_all([
            tx_of(gw, TxType.ttPAYMENT,
                  {sfAmount: iou(10_000), sfDestination: t.account_id})
            for t in traders
        ])
        check_identity(close())

        # -- baseline closes: crossfire, no subscriptions -----------------
        live_offers: list[tuple] = []  # (owner, offer seq)
        rnd_rate = [1, 2, 3]

        def crossfire(i):
            """One close's worth of book churn."""
            txs = []
            a, b, c = (traders[i % 4], traders[(i + 1) % 4],
                       traders[(i + 2) % 4])
            # a sells USD for XRP at a rotating rate (new tier, and on
            # fresh pairs every few closes a brand-new book)
            rate = rnd_rate[i % 3]
            live_offers.append((a, seqs.setdefault(a.account_id, 1)))
            txs.append(tx_of(a, TxType.ttOFFER_CREATE,
                             {sfTakerPays: drops(10 * rate * M),
                              sfTakerGets: iou(10)}))
            if i % 2 == 0:
                # b crosses the best tier (partial fill / tier consume)
                txs.append(tx_of(b, TxType.ttOFFER_CREATE,
                                 {sfTakerPays: iou(5),
                                  sfTakerGets: drops(5 * 3 * M)}))
            if i % 3 == 2 and live_offers:
                owner, oseq = live_offers.pop(0)
                txs.append(tx_of(owner, TxType.ttOFFER_CANCEL,
                                 {sfOfferSequence: oseq}))
            if i % 4 == 3:
                # reverse-direction book: c sells XRP for USD, priced
                # NOT to cross (demands 2 USD/XRP vs market's ~0.3-1)
                txs.append(tx_of(c, TxType.ttOFFER_CREATE,
                                 {sfTakerPays: iou(20),
                                  sfTakerGets: drops(10 * M)}))
            return txs

        baseline_n = n_closes // 2
        for i in range(baseline_n):
            submit_all(crossfire(i))
            check_identity(close())
        baseline_p50 = statistics.median(close_times[-baseline_n:])

        # -- subscribed flood ---------------------------------------------
        # drive the publisher synchronously per close (normally it runs
        # on a jtUPDATE_PF job): deliveries become deterministic and the
        # close timing below still never includes pathfinding
        from stellard_tpu.rpc.infosub import SubscriptionManager

        mgr = SubscriptionManager(node.ops)  # node.subs waits for serve()
        node.ops.on_ledger_closed.remove(mgr._pub_ledger)
        mgr.path_plane = plane
        boxes = [[] for _ in range(n_subs)]
        for j, box in enumerate(boxes):
            sub = InfoSub(box.append)
            mgr.create_path_request(sub, {
                "src": traders[j % 4].account_id,
                "dst": traders[(j + 1) % 4].account_id,
                "dst_amount": iou(5),
            })
        spam_box: list = []
        spammer = InfoSub(spam_box.append, client_ip="6.6.6.6")
        while not node.rpc_resources.is_throttled(("6.6.6.6", 0)):
            node.rpc_resources.charge(("6.6.6.6", 0), FEE_PATH_FIND)
        mgr.create_path_request(spammer, {
            "src": traders[0].account_id,
            "dst": traders[1].account_id,
            "dst_amount": iou(5),
        })

        flood_times: list[float] = []
        for i in range(baseline_n, n_closes):
            submit_all(crossfire(i))
            closed = close()
            flood_times.append(close_times[-1])
            check_identity(closed)
            before = plane.reranked
            mgr._pub_path_updates(closed)
            if plane.reranked <= before:
                bad.append(f"seq {closed.seq}: close re-ranked nothing")

        # -- gates ---------------------------------------------------------
        counters = plane.index.counters()
        if not counters["incremental_advances"]:
            bad.append("incremental index never advanced incrementally "
                       f"(counters: {counters})")
        if counters["full_rebuilds"] > 2:
            bad.append(f"index kept falling back to full scans: {counters}")
        delivered = sum(len(b) for b in boxes)
        want = (n_closes - baseline_n) * n_subs
        if delivered < want:
            bad.append(f"polite subscribers got {delivered}/{want} updates")
        if any(m.get("type") != "path_find" for b in boxes for m in b):
            bad.append("non-path_find message on a path subscription")
        if spam_box:
            bad.append(f"throttled flooder still got {len(spam_box)} updates")
        if plane.shed_throttled < (n_closes - baseline_n):
            bad.append(f"resource plane shed only {plane.shed_throttled} "
                       "flooder updates")
        flood_p50 = statistics.median(flood_times)
        if flood_p50 > max(baseline_p50 * 3.0, baseline_p50 + 0.05):
            bad.append(
                f"close cadence regressed: p50 {flood_p50 * 1e3:.1f}ms "
                f"subscribed vs {baseline_p50 * 1e3:.1f}ms baseline")
        if bad:
            for b in bad:
                print(f"path smoke: {b}", file=sys.stderr)
            return 1
        print(
            f"path smoke OK: {n_closes} crossfire closes identical to the "
            f"full scan (advances={counters['incremental_advances']} "
            f"carries={counters['carries']} rebuilds="
            f"{counters['full_rebuilds']} rereads={counters['book_rereads']}) "
            f"| {delivered} updates to {n_subs} subs, flooder shed "
            f"{plane.shed_throttled}x | close p50 "
            f"{baseline_p50 * 1e3:.1f}ms -> {flood_p50 * 1e3:.1f}ms"
        )
        return 0
    finally:
        node.stop()


if __name__ == "__main__":
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 12
    sys.exit(run_smoke(n))
