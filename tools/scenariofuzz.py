"""Coverage-guided scenario fuzzing CLI (testkit/search.py front end).

Modes:

  --smoke            tier-1 gate (tools/tier1.sh): replay the permanent
                     corpus, run a bounded ARMED sweep that must find
                     the planted synthetic bug and shrink it to its
                     known minimal schedule (then prove the entry
                     replays as a violation while armed and clean once
                     disarmed — the found→shrunk→fixed→pinned loop,
                     end to end), and run the coverage-guided vs
                     uniform generation comparison (novelty bias must
                     win). Budget knob: FUZZ_N env (default 30
                     generated scenarios per phase) — raise it for
                     longer offline sweeps, e.g. FUZZ_N=300.
  --sweep N          offline bug hunting: N generated scenarios,
                     coverage-guided, shrinking every first-of-kind
                     violation; violations land as corpus-entry JSON in
                     --corpus-out (default /tmp, NOT the checked-in
                     corpus — triage first, then move them in).
  --replay NAME      replay a corpus entry (checked-in name or a JSON
                     file path) and re-check the invariant registry.
  --compare N        just the guided-vs-uniform comparison.
  --soak [min] [sd]  the `chaos` scenario on the REAL TCP+TLS net
                     (absorbed from tools/chaos_soak.py, which remains
                     as a deprecation shim).

Every phase prints one JSON line; --smoke exits non-zero on any gate
failure. Deterministic: same seed, same machine-independent output
(PYTHONHASHSEED-proof, pinned by tests/test_search.py).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from stellard_tpu.testkit.scenario import (  # noqa: E402
    SYNTH_BUG,
    Scenario,
    run_simnet,
)
from stellard_tpu.testkit.scenarios import (  # noqa: E402
    build_scenario,
    load_corpus,
)
from stellard_tpu.testkit.search import (  # noqa: E402
    SYNTH_THRESHOLD,
    Violation,
    check_invariants,
    corpus_entry,
    counter_vector,
    coverage_comparison,
    shrink_scenario,
    sweep,
    write_corpus_entry,
)


def fail(msg: str) -> None:
    print(f"SCENARIO FUZZ FAILED: {msg}", file=sys.stderr)
    raise SystemExit(2)


def emit(obj: dict) -> None:
    print(json.dumps(obj), flush=True)


def replay_corpus() -> int:
    """Replay every checked-in corpus entry; each must honor its
    `expect` contract ("pass": no invariant violations)."""
    n = 0
    for name, entry in load_corpus().items():
        scn = build_scenario(name)
        card = run_simnet(scn)
        viols = check_invariants(scn, card)
        ok = (not viols) if entry.get("expect", "pass") == "pass" else \
            any(v.invariant == entry["invariant"] for v in viols)
        emit({
            "phase": "corpus", "entry": name,
            "invariant": entry.get("invariant"),
            "expect": entry.get("expect", "pass"),
            "ok": ok,
            "violations": [f"{v.invariant}: {v.detail}" for v in viols],
        })
        if not ok:
            fail(f"corpus entry {name} broke its contract "
                 f"(expect={entry.get('expect')}, got {viols})")
        n += 1
    return n


def smoke(seed: int, n: int) -> None:
    t0 = time.perf_counter()

    # (1) the permanent corpus replays clean (the real bugs the sweep
    # found stay fixed)
    n_corpus = replay_corpus()

    # (2) armed sweep: the planted synthetic bug must be FOUND and
    # SHRUNK; any non-synthetic violation is a NEW real bug -> red
    res = sweep(seed, n, guided=True, allow_synth=True, shrink=True,
                determinism_check=True)
    synth = [v for v in res["violations"]
             if v["invariant"] == "synthetic_bug"]
    real = [v for v in res["violations"]
            if v["invariant"] != "synthetic_bug"]
    emit({
        "phase": "armed_sweep", "seed": seed, "runs": n,
        "distinct_signatures": res["distinct_signatures"],
        "synthetic_found": len(synth), "real_violations": len(real),
    })
    if real:
        for v in real:
            emit({"phase": "real_violation", "invariant": v["invariant"],
                  "detail": v["detail"], "scenario": v["scenario"]})
        fail(f"{len(real)} real invariant violation(s) found — triage, "
             f"fix, and pin them as corpus entries")
    if not synth:
        fail(f"planted synthetic bug not found in {n} runs "
             f"(seed {seed}) — the sweep lost its ground truth")

    # (3) the first synthetic find carries a full shrink: verify it
    # reached the KNOWN minimal schedule (plant events only, magnitudes
    # summing to exactly the threshold)
    shrunk = next(v for v in synth if "shrunk" in v)
    minimal = Scenario.from_json(shrunk["shrunk"])
    events = minimal.schedule.events if minimal.schedule else []
    kinds = sorted({e.kind for e in events})
    total = sum(e.args[0] for e in events if e.kind == "synth_plant")
    emit({
        "phase": "shrink", "iteration": shrunk["iteration"],
        "events": len(events), "kinds": kinds, "plant_total": total,
        "shrink_attempts": len(shrunk["shrink_trajectory"]),
        "workload": minimal.workload,
    })
    if kinds != ["synth_plant"] or total != SYNTH_THRESHOLD:
        fail(f"shrinker did not reach the known minimum (kinds {kinds}, "
             f"plant total {total}, expected only synth_plant summing "
             f"to {SYNTH_THRESHOLD})")
    if minimal.workload is not None or minimal.n_peers or \
            minimal.byzantine or minimal.n_followers:
        fail("shrinker left non-essential axes on the synthetic repro")

    # (4) the corpus-entry loop end to end: armed replay reproduces the
    # violation deterministically; disarmed ("the fix") replays clean
    entry = corpus_entry(
        minimal, Violation("synthetic_bug", shrunk["detail"]),
        found={"fuzz_seed": seed, "iteration": shrunk["iteration"]},
        expect="violation",
    )
    SYNTH_BUG["armed"] = True
    try:
        card = run_simnet(Scenario.from_json(entry["scenario"]))
        armed_viols = check_invariants(minimal, card)
    finally:
        SYNTH_BUG["armed"] = False
    card = run_simnet(Scenario.from_json(entry["scenario"]))
    fixed_viols = check_invariants(minimal, card)
    emit({
        "phase": "entry_contract",
        "armed_reproduces": any(
            v.invariant == "synthetic_bug" for v in armed_viols
        ),
        "disarmed_clean": not fixed_viols,
    })
    if not any(v.invariant == "synthetic_bug" for v in armed_viols):
        fail("shrunk corpus entry does not reproduce while armed")
    if fixed_viols:
        fail(f"shrunk corpus entry not clean after the fix: {fixed_viols}")

    # (5) the novelty bias earns its keep: distinct scorecard coverage
    # states per N runs, guided vs uniform, same seed
    cmp_res = coverage_comparison(seed, n)
    emit({"phase": "coverage_comparison", **cmp_res})
    if cmp_res["guided_distinct"] < cmp_res["uniform_distinct"]:
        fail(f"coverage-guided generation ({cmp_res['guided_distinct']} "
             f"states) lost to uniform ({cmp_res['uniform_distinct']})")

    emit({
        "fuzz_smoke": "ok", "seed": seed, "runs_per_phase": n,
        "corpus_entries": n_corpus,
        "synthetic_found_and_shrunk": True,
        "guided_distinct": cmp_res["guided_distinct"],
        "uniform_distinct": cmp_res["uniform_distinct"],
        "wall_s": round(time.perf_counter() - t0, 1),
    })


def offline_sweep(seed: int, n: int, synth: bool, corpus_out: str) -> None:
    def progress(p):
        if p["violations"] or p["iteration"] % 10 == 9:
            emit({"phase": "progress", **p})

    res = sweep(seed, n, guided=True, allow_synth=synth, shrink=True,
                determinism_check=True, on_progress=progress)
    written = []
    for v in res["violations"]:
        if "entry" in v:
            written.append(write_corpus_entry(v["entry"], corpus_out))
    emit({
        "phase": "sweep_done", "seed": seed, "runs": n,
        "distinct_signatures": res["distinct_signatures"],
        "violations": [
            {"iteration": v["iteration"], "invariant": v["invariant"],
             "detail": v["detail"]}
            for v in res["violations"]
        ],
        "corpus_entries_written": written,
    })
    raise SystemExit(1 if res["violations"] else 0)


def replay(target: str) -> None:
    if os.path.exists(target):
        with open(target) as f:
            entry = json.load(f)
        scn = Scenario.from_json(entry["scenario"])
    else:
        entry = load_corpus().get(target)
        if entry is None:
            fail(f"no corpus entry or file named {target!r}")
        scn = build_scenario(target)
    card = run_simnet(scn)
    viols = check_invariants(scn, card)
    emit({
        "phase": "replay", "entry": entry["name"],
        "violations": [f"{v.invariant}: {v.detail}" for v in viols],
        # the full flattened counter view, for triage
        "counters": counter_vector(card),
        "scorecard": card,
    })
    expect = entry.get("expect", "pass")
    ok = (not viols) if expect == "pass" else bool(viols)
    raise SystemExit(0 if ok else 1)


def soak(minutes: float, seed: int) -> None:
    """The chaos scenario on the REAL TCP net (ex tools/chaos_soak.py)."""
    from stellard_tpu.testkit.scenarios import scenario_chaos
    from stellard_tpu.testkit.tcpnet import run_tcp

    steps = max(60, int(minutes * 60))  # 1 step ~= 1 second
    scn = scenario_chaos(seed=seed, steps=steps, kill_every=45,
                         downtime=5)
    card = run_tcp(scn)
    card["chaos_minutes"] = minutes
    card["summary"] = True
    emit(card)
    if not card["converged"]:
        raise SystemExit(f"no convergence: {card['validated_seqs']}")
    if not card["single_hash"]:
        raise SystemExit(f"FORK at {card['final_seq']}")


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--sweep", type=int, metavar="N")
    ap.add_argument("--compare", type=int, metavar="N")
    ap.add_argument("--replay", metavar="NAME_OR_FILE")
    ap.add_argument("--soak", nargs="*", metavar=("MINUTES", "SEED"))
    ap.add_argument("--seed", type=int,
                    default=int(os.environ.get("FUZZ_SEED", "7")))
    ap.add_argument("--synth", action="store_true",
                    help="arm the planted test-only bug in --sweep")
    ap.add_argument("--corpus-out", default="/tmp/scenariofuzz-corpus")
    args = ap.parse_args()

    if args.smoke:
        smoke(args.seed, int(os.environ.get("FUZZ_N", "30")))
    elif args.sweep is not None:
        offline_sweep(args.seed, args.sweep, args.synth, args.corpus_out)
    elif args.compare is not None:
        emit(coverage_comparison(args.seed, args.compare))
    elif args.replay is not None:
        replay(args.replay)
    elif args.soak is not None:
        minutes = float(args.soak[0]) if len(args.soak) > 0 else 12.0
        seed = int(args.soak[1]) if len(args.soak) > 1 else 7
        soak(minutes, seed)
    else:
        ap.print_help()


if __name__ == "__main__":
    main()
