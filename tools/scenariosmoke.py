"""Tier-1 scenario smoke: the adversarial plane as a regression gate.

Runs three seeded deterministic simnet scenarios — one partition/kill,
one byzantine, one cold-node catch-up — each TWICE with the same seed,
asserting:

- convergence: every honest validator quorum-validated on ONE identical
  chain (converged + single_hash);
- determinism: the two runs of one seed produce byte-identical
  scorecards (the FoundationDB property — a failure here means a wall
  clock or unseeded RNG leaked into the deterministic transport);
- anti-vacuity: the hostile inputs actually happened — byzantine
  defense counters, catch-up retry/backoff/garbage counters, and the
  partition's drop counters are all nonzero. A scenario that silently
  stopped injecting faults must FAIL, not greenwash.

Prints one JSON line per scenario run plus a summary line; exit 0 only
when every gate holds. Runtime: a few seconds (the simnet is
in-process and discrete-time).

Usage: python tools/scenariosmoke.py [seed]
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from stellard_tpu.testkit import build_scenario, run_simnet  # noqa: E402

SEED = int(sys.argv[1]) if len(sys.argv) > 1 else 7


def fail(msg: str) -> None:
    print(f"SCENARIO SMOKE FAILED: {msg}", file=sys.stderr)
    raise SystemExit(2)


def run_twice(name: str):
    a = run_simnet(build_scenario(name, seed=SEED))
    b = run_simnet(build_scenario(name, seed=SEED))
    print(json.dumps(a), flush=True)
    if json.dumps(a, sort_keys=True) != json.dumps(b, sort_keys=True):
        for k in sorted(set(a) | set(b)):
            if a.get(k) != b.get(k):
                print(f"  diverged field {k!r}: {a.get(k)!r} != "
                      f"{b.get(k)!r}", file=sys.stderr)
        fail(f"{name}: scorecard not deterministic for seed {SEED}")
    if not a["converged"]:
        fail(f"{name}: honest validators never converged "
             f"({a['validated_seqs']})")
    if not a["single_hash"]:
        fail(f"{name}: FORK at seq {a['final_seq']}")
    return a


def main() -> None:
    # (1) partitions + rotating kills under flood
    card = run_twice("partition_kills")
    if card["net"]["dropped_link"] == 0 or card["net"]["dropped_down"] == 0:
        fail("partition_kills: no partition/kill drops — faults vacuous")
    if card["committed"] != card["submitted"]:
        # every client submission must land on the final chain — the
        # 0.85-threshold era ended when this gate found (and we fixed)
        # LocalTxs dropping fork-reverted txs at repair
        fail(f"partition_kills: only {card['committed']}/"
             f"{card['submitted']} committed")

    # (2) byzantine peer: every behavior leaves defense evidence
    card = run_twice("byzantine")
    byz = card["byzantine"]
    for kind in ("bad_validation_sig", "untrusted_validation",
                 "stale_validation", "oversized_txset",
                 "malformed_frame", "duplicate_proposal",
                 "conflicting_proposal"):
        if byz.get(kind, 0) <= 0:
            fail(f"byzantine: defense counter {kind} never fired "
                 f"(anti-vacuity)")
    for nid, emitted in card["byzantine_emitted"].items():
        for behavior, n in emitted.items():
            if n <= 0:
                fail(f"byzantine: slot {nid} behavior {behavior} "
                     f"emitted nothing")
    if card["committed"] != card["submitted"]:
        fail(f"byzantine: {card['submitted'] - card['committed']} "
             f"client txs lost under hostile peer")

    # (2b) spec-pool under faults (PR 8 follow-on): the chaos and
    # partition scenarios re-run with [spec] workers=2 thread pools on
    # every honest validator. Worker timing is wall-clock, so the
    # splice/retry counters are not replay-deterministic — the gate is
    # HASH IDENTITY: the parallel run must converge on the exact chain
    # the serial run of the same seed produced, under the same faults.
    for name in ("chaos", "partition_kills"):
        serial = run_simnet(build_scenario(name, seed=SEED))
        spec_scn = build_scenario(name, seed=SEED)
        spec_scn.spec_workers = 2
        spec_card = run_simnet(spec_scn)
        print(json.dumps(spec_card), flush=True)
        if not spec_card["converged"]:
            fail(f"{name}+spec: validators never converged "
                 f"({spec_card['validated_seqs']})")
        if not spec_card["single_hash"]:
            fail(f"{name}+spec: FORK at seq {spec_card['final_seq']}")
        if (spec_card["final_seq"] != serial["final_seq"]
                or spec_card["final_hash"] != serial["final_hash"]):
            fail(f"{name}+spec: workers=2 chain diverged from serial "
                 f"(seq {spec_card['final_seq']} vs "
                 f"{serial['final_seq']}, hash "
                 f"{spec_card['final_hash']} vs {serial['final_hash']})")
        if spec_card.get("spec", {}).get("dispatched", 0) <= 0:
            fail(f"{name}+spec: worker pool dispatched nothing "
                 f"(anti-vacuity)")
        if spec_card["committed"] != spec_card["submitted"]:
            fail(f"{name}+spec: only {spec_card['committed']}/"
                 f"{spec_card['submitted']} committed under workers=2")

    # (3) cold-node catch-up under fire
    card = run_twice("cold_catchup")
    cu = card["catchup"]
    if not cu["synced"]:
        fail("cold_catchup: cold node never joined the validated chain")
    sf = cu["segfetch"]
    if sf["records"] <= 0:
        fail("cold_catchup: segment path transferred nothing")
    if sf["garbage_peers"] < 1:
        fail("cold_catchup: garbage server never detected")
    if sf["timeouts"] < 1 or sf["backoffs"] < 1 or sf["peer_switches"] < 2:
        fail(f"cold_catchup: kill-mid-sync retry path vacuous ({sf})")

    print(json.dumps({
        "scenario_smoke": "ok", "seed": SEED,
        "scenarios": ["partition_kills", "byzantine",
                      "chaos+spec2", "partition_kills+spec2",
                      "cold_catchup"],
        "deterministic": True,
    }), flush=True)


if __name__ == "__main__":
    main()
