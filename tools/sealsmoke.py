#!/usr/bin/env python3
"""Seal-equivalence smoke gate (tools/tier1.sh).

Boots a standalone node with the incremental seal ON (the default),
floods ~200 payments through the full async pipeline closing every 50,
then SHADOW-RECOMPUTES every closed ledger's hash with a full seal:
both trees are rebuilt from their items into fresh nodes (no cached
hashes, no structural sharing with the live chain) and re-hashed
through the plain host hasher. Any divergence between the incremental
seal's adopted roots and the from-scratch full seal fails the gate —
a wrong pre-hashed node must fail CI, not a consensus round.

Exit 0 on byte equality for every close; 1 otherwise.
"""

from __future__ import annotations

import sys

import os

# runnable as "python tools/sealsmoke.py" from anywhere: a script in
# tools/ does not get the repo root on sys.path by itself
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def full_seal_hashes(ledger) -> tuple[bytes, bytes, bytes]:
    """(tx_hash, account_hash, ledger_hash) recomputed from scratch:
    fresh trees built leaf-by-leaf from the ledger's items, hashed by
    the default host hasher — zero reuse of the live chain's nodes."""
    from stellard_tpu.state.shamap import SHAMap, SHAMapItem, TNType
    from stellard_tpu.utils.hashes import HP_LEDGER_MASTER, prefix_hash
    from stellard_tpu.protocol.serializer import Serializer

    tx = SHAMap(TNType.TX_MD)
    for leaf in ledger.tx_map.leaves():
        tx.set_item(SHAMapItem(leaf.item.tag, leaf.item.data), leaf.type)
    st = SHAMap(TNType.ACCOUNT_STATE)
    for item in ledger.state_map.items():
        st.set_item(SHAMapItem(item.tag, item.data))
    tx_hash, account_hash = tx.get_hash(), st.get_hash()
    # header re-serialized with the recomputed tree hashes
    s = Serializer()
    s.add32(ledger.seq)
    s.add64(ledger.tot_coins)
    s.add64(ledger.fee_pool)
    s.add32(ledger.inflation_seq)
    s.add_raw(ledger.parent_hash)
    s.add_raw(tx_hash)
    s.add_raw(account_hash)
    s.add32(ledger.parent_close_time)
    s.add32(ledger.close_time)
    s.add8(ledger.close_resolution)
    s.add8(ledger.close_flags)
    return tx_hash, account_hash, prefix_hash(HP_LEDGER_MASTER, s.data())


def run_smoke(n_txs: int = 200) -> int:
    import threading

    from stellard_tpu.node.config import Config
    from stellard_tpu.node.node import Node
    from stellard_tpu.protocol.formats import TxType
    from stellard_tpu.protocol.keys import KeyPair
    from stellard_tpu.protocol.sfields import sfAmount, sfDestination
    from stellard_tpu.protocol.stamount import STAmount
    from stellard_tpu.protocol.sttx import SerializedTransaction

    node = Node(Config(tree_incremental_seal=True)).setup()
    closed_seqs = []
    try:
        master = KeyPair.from_passphrase("masterpassphrase")
        dests = [
            KeyPair.from_passphrase(f"seal-smoke-{i}").account_id
            for i in range(8)
        ]
        done = threading.Semaphore(0)

        def cb(tx, ter, applied):
            done.release()

        for chunk in range(0, n_txs, 50):
            txs = []
            for i in range(chunk, min(chunk + 50, n_txs)):
                tx = SerializedTransaction.build(
                    TxType.ttPAYMENT, master.account_id, 1 + i, 10,
                    {sfAmount: STAmount.from_drops(250_000_000),
                     sfDestination: dests[i % len(dests)]},
                )
                tx.sign(master)
                txs.append(tx)
            for tx in txs:
                node.ops.submit_transaction(tx, cb)
            for _ in txs:
                done.acquire()
            closed, _results = node.ops.accept_ledger()
            closed_seqs.append(closed.seq)
        if not node.close_pipeline.flush(timeout=60):
            print("seal smoke: close pipeline failed to drain",
                  file=sys.stderr)
            return 1

        lm = node.ledger_master
        tree = lm.tree_json()
        bad = 0
        for seq in closed_seqs:
            led = lm.get_ledger_by_seq(seq)
            if led is None:
                print(f"seal smoke: closed ledger {seq} missing",
                      file=sys.stderr)
                bad += 1
                continue
            tx_h, st_h, lh = full_seal_hashes(led)
            if (tx_h != led.tx_map.get_hash()
                    or st_h != led.state_map.get_hash()
                    or lh != led.hash()):
                print(
                    f"seal smoke: ledger {seq} DIVERGED — incremental "
                    f"seal {led.hash().hex()[:16]} vs full seal "
                    f"{lh.hex()[:16]}", file=sys.stderr,
                )
                bad += 1
        if bad:
            return 1
        print(
            f"seal smoke OK: {len(closed_seqs)} closes byte-identical to "
            f"the full-seal shadow (adopted={tree.get('seal_adopted', 0)} "
            f"drains={tree.get('drains', 0)} "
            f"drained_nodes={tree.get('drained_nodes', 0)})"
        )
        if not tree.get("seal_adopted"):
            # equality of a seal that never engaged proves nothing — the
            # gate must exercise the adoption path, not vacuously pass
            print("seal smoke: incremental seal never adopted a root",
                  file=sys.stderr)
            return 1
        return 0
    finally:
        node.stop()


if __name__ == "__main__":
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 200
    sys.exit(run_smoke(n))
