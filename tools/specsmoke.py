#!/usr/bin/env python3
"""Parallel-speculation equivalence smoke gate (tools/tier1.sh).

Boots a standalone node with the Block-STM worker pool ON
([spec] workers=4, process transport), floods ~200 payments through the
full async pipeline closing every 50, then runs the SAME workload with
the SAME pinned close-time schedule through a workers=1 (serial inline
speculation) node. Every close must be byte-identical between the two
runs — ledger hash AND per-tx results — and the parallel run's splice
rate must not regress: the pool's job is to produce the same records
the serial path would have, so a close that falls back more often under
the pool is a scheduler bug even when the hashes happen to agree.

The gate also refuses to pass vacuously: the parallel run must actually
have dispatched through the pool and committed optimistically (not
completed every window via the forced-serial drain).

Exit 0 on per-close byte equality + splice parity; 1 otherwise.
"""

from __future__ import annotations

import sys

import os

# runnable as "python tools/specsmoke.py" from anywhere: a script in
# tools/ does not get the repo root on sys.path by itself
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run_flood(workers: int, n_txs: int, chunk: int = 50,
              transport: str = "ring"):
    """One standalone-node flood; -> per-close evidence + counters."""
    import hashlib
    import threading

    from stellard_tpu.node.config import Config
    from stellard_tpu.node.node import Node
    from stellard_tpu.protocol.formats import TxType
    from stellard_tpu.protocol.keys import KeyPair
    from stellard_tpu.protocol.sfields import sfAmount, sfDestination
    from stellard_tpu.protocol.stamount import STAmount
    from stellard_tpu.protocol.sttx import SerializedTransaction

    node = Node(Config(spec_workers=workers, spec_mode="process",
                       spec_transport=transport)).setup()
    closes = []
    try:
        # deterministic close-time schedule: the two runs happen
        # seconds apart and must close on identical times to be
        # byte-comparable
        closes_done = [0]
        node.ops.network_time = lambda: 900_000_000 + closes_done[0] * 30

        master = KeyPair.from_passphrase("masterpassphrase")
        senders = [KeyPair.from_passphrase(f"spec-smoke-s{i}")
                   for i in range(8)]
        dests = [KeyPair.from_passphrase(f"spec-smoke-d{i}").account_id
                 for i in range(8)]
        done = threading.Semaphore(0)

        def cb(tx, ter, applied):
            done.release()

        def submit_all(txs):
            for tx in txs:
                node.ops.submit_transaction(tx, cb)
            for _ in txs:
                done.acquire()

        # setup (unmeasured, still compared): fund the senders
        fund = []
        for i, s in enumerate(senders):
            tx = SerializedTransaction.build(
                TxType.ttPAYMENT, master.account_id, 1 + i, 10,
                {sfAmount: STAmount.from_drops(5_000_000_000),
                 sfDestination: s.account_id},
            )
            tx.sign(master)
            fund.append(tx)
        submit_all(fund)
        node.ops.accept_ledger()
        closes_done[0] += 1

        seqs = {s.account_id: 1 for s in senders}
        built = 0
        lm = node.ledger_master
        while built < n_txs:
            txs = []
            for _ in range(min(chunk, n_txs - built)):
                s = senders[built % len(senders)]
                tx = SerializedTransaction.build(
                    TxType.ttPAYMENT, s.account_id, seqs[s.account_id],
                    10,
                    {sfAmount: STAmount.from_drops(1_000_000),
                     sfDestination: dests[built % len(dests)]},
                )
                tx.sign(s)
                seqs[s.account_id] += 1
                txs.append(tx)
                built += 1
            before = lm.delta_stats.snapshot()
            submit_all(txs)
            closed, results = node.ops.accept_ledger()
            closes_done[0] += 1
            after = lm.delta_stats.snapshot()
            digest = hashlib.sha256()
            for txid in sorted(results):
                digest.update(txid + bytes([int(results[txid]) & 0xFF]))
            closes.append({
                "seq": closed.seq,
                "hash": closed.hash().hex(),
                "results": digest.hexdigest(),
                "n": len(results),
                "spliced": after["spliced"] - before["spliced"],
                "fallback": after["fallback"] - before["fallback"],
            })
        spec = node.spec_executor.get_json()
        return closes, spec
    finally:
        node.stop()


def run_smoke(n_txs: int = 200) -> int:
    par_closes, par_spec = run_flood(4, n_txs)
    ser_closes, _ = run_flood(1, n_txs)

    bad = 0
    if len(par_closes) != len(ser_closes):
        print(
            f"spec smoke: close count diverged — parallel "
            f"{len(par_closes)} vs serial {len(ser_closes)}",
            file=sys.stderr,
        )
        return 1
    for p, s in zip(par_closes, ser_closes):
        if p["hash"] != s["hash"] or p["results"] != s["results"]:
            print(
                f"spec smoke: ledger {p['seq']} DIVERGED — workers=4 "
                f"{p['hash'][:16]} vs serial {s['hash'][:16]}",
                file=sys.stderr,
            )
            bad += 1
        if p["spliced"] < s["spliced"]:
            print(
                f"spec smoke: ledger {p['seq']} splice-rate REGRESSED — "
                f"workers=4 spliced {p['spliced']}/{p['n']} vs serial "
                f"{s['spliced']}/{s['n']}", file=sys.stderr,
            )
            bad += 1
    if bad:
        return 1

    # anti-vacuity: the pool must have done the speculating
    if par_spec["dispatched"] < n_txs:
        print(
            f"spec smoke: pool only saw {par_spec['dispatched']}/{n_txs} "
            f"txs — the parallel path was not exercised", file=sys.stderr,
        )
        return 1
    if par_spec["serial_fallbacks"] > n_txs // 2:
        print(
            f"spec smoke: {par_spec['serial_fallbacks']} serial fallbacks "
            f"out of {n_txs} — the pool is not committing optimistically",
            file=sys.stderr,
        )
        return 1
    # ring anti-vacuity (ISSUE 16): the parallel run rode the shared-
    # memory transport, its counters moved, and no slot tore — a smoke
    # that quietly fell back to pipes (or never touched the rings)
    # would prove nothing about the zero-pickle dispatch path
    ring = par_spec.get("ring") or {}
    if par_spec.get("transport") != "ring" or not ring.get("msgs_sent"):
        print(
            f"spec smoke: shared-memory transport not exercised — "
            f"transport={par_spec.get('transport')!r} "
            f"ring_msgs={ring.get('msgs_sent', 0)}", file=sys.stderr,
        )
        return 1
    if ring.get("torn_slots"):
        print(
            f"spec smoke: {ring['torn_slots']} torn ring slots on a "
            f"healthy pool", file=sys.stderr,
        )
        return 1
    spliced = sum(c["spliced"] for c in par_closes)
    total = sum(c["n"] for c in par_closes)
    print(
        f"spec smoke OK: {len(par_closes)} closes byte-identical to the "
        f"serial shadow at workers=4 (spliced={spliced}/{total} "
        f"committed={par_spec['committed']} retries={par_spec['retries']} "
        f"aborts={par_spec['validation_aborts']} "
        f"serial_fallbacks={par_spec['serial_fallbacks']} "
        f"forced_drains={par_spec['drains_forced']} "
        f"ring_msgs={ring['msgs_sent']}+{ring.get('msgs_recv', 0)} "
        f"ring_kb={(ring.get('bytes_sent', 0) + ring.get('bytes_recv', 0)) // 1024})"
    )
    return 0


if __name__ == "__main__":
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 200
    sys.exit(run_smoke(n))
