#!/usr/bin/env python3
"""Storage crash-recovery smoke gate (tools/tier1.sh).

For each durable NodeStore backend (segstore, cpplog):

1. spawn a child process that boots a standalone file-backed node and
   floods payments through the full async pipeline, closing every 25
   and printing each durable close;
2. SIGKILL the child mid-flood — with closes landing continuously, the
   kill lands mid-flush often enough to leave torn tails;
3. reopen the stores in THIS process and assert the durability
   invariant the close pipeline's stage order promises: every ledger
   whose txdb header committed (header commits AFTER the NodeStore
   flush, in drain order) must fully resolve from the reopened store —
   header hash, state tree, tx tree, every node verified against its
   content hash by Ledger.load.

A torn tail must be truncated away silently (both backends recover by
replay); a ledger that persisted before the kill but cannot resolve
after reopen is a storage-plane corruption bug and fails the gate.

Exit 0 when both backends pass; 1 otherwise.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

CLOSES_BEFORE_KILL = 4
MIN_RESOLVED = 3


def child_flood(backend: str, state_dir: str) -> None:
    """Flood forever (until killed), printing CLOSED <seq> per close."""
    import threading

    from stellard_tpu.node.config import Config
    from stellard_tpu.node.node import Node
    from stellard_tpu.protocol.formats import TxType
    from stellard_tpu.protocol.keys import KeyPair
    from stellard_tpu.protocol.sfields import sfAmount, sfDestination
    from stellard_tpu.protocol.stamount import STAmount
    from stellard_tpu.protocol.sttx import SerializedTransaction

    node = Node(Config(
        node_db_type=backend,
        node_db_path=os.path.join(state_dir, "nodestore"),
        database_path=os.path.join(state_dir, "stellard.db"),
        # small segments so the kill also exercises roll boundaries
        **({"node_db_segment_mb": 1} if backend == "segstore" else {}),
    )).setup()
    master = KeyPair.from_passphrase("masterpassphrase")
    dests = [KeyPair.from_passphrase(f"storage-smoke-{i}").account_id
             for i in range(8)]
    done = threading.Semaphore(0)

    def cb(tx, ter, applied):
        done.release()

    seq = 1
    while True:
        txs = []
        for i in range(25):
            tx = SerializedTransaction.build(
                TxType.ttPAYMENT, master.account_id, seq, 10,
                {sfAmount: STAmount.from_drops(250_000_000),
                 sfDestination: dests[i % len(dests)]},
            )
            tx.sign(master)
            txs.append(tx)
            seq += 1
        for tx in txs:
            node.ops.submit_transaction(tx, cb)
        for _ in txs:
            done.acquire()
        node.ops.accept_ledger()
        # report the last DURABLY persisted close (pipeline drained):
        # the parent kills somewhere after CLOSES_BEFORE_KILL of these
        node.close_pipeline.flush(timeout=60)
        print(f"CLOSED {node.ledger_master.closed_ledger().seq}",
              flush=True)


def verify_reopen(backend: str, state_dir: str) -> int:
    """-> number of fully-resolved persisted ledgers; raises on any
    persisted-but-unresolvable ledger."""
    from stellard_tpu.node.txdb import TxDatabase
    from stellard_tpu.nodestore import make_database
    from stellard_tpu.state.ledger import Ledger

    db = make_database(
        type=backend, path=os.path.join(state_dir, "nodestore")
    )
    txdb = TxDatabase(os.path.join(state_dir, "stellard.db"))
    try:
        seqs = txdb.ledger_seqs()
        if not seqs:
            raise AssertionError("no persisted ledgers after kill")
        resolved = 0
        for seq in seqs:
            hdr = txdb.get_ledger_header(seq=seq)
            led = Ledger.load(db, hdr["hash"])  # verifies every node
            if led.hash() != hdr["hash"]:
                raise AssertionError(
                    f"seq {seq}: reloaded hash mismatch"
                )
            resolved += 1
        stats = getattr(db.backend, "get_json", lambda: {})()
        print(f"  [{backend}] reopened: {resolved} ledgers resolved, "
              f"replayed_records={stats.get('replayed_records', 'n/a')} "
              f"from_checkpoint={stats.get('opened_from_checkpoint')}")
        return resolved
    finally:
        db.close()
        txdb.close()


def run_one(backend: str) -> bool:
    state_dir = tempfile.mkdtemp(prefix=f"storage-smoke-{backend}-")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    child = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--child", backend,
         state_dir],
        stdout=subprocess.PIPE, text=True, env=env, cwd=REPO,
    )
    closes = 0
    deadline = time.monotonic() + 240
    try:
        while closes < CLOSES_BEFORE_KILL:
            if time.monotonic() > deadline:
                raise AssertionError(
                    f"[{backend}] child made {closes} closes before the "
                    f"240s budget — flood stalled"
                )
            line = child.stdout.readline()
            if not line:
                raise AssertionError(
                    f"[{backend}] child exited early (rc={child.poll()})"
                )
            if line.startswith("CLOSED"):
                closes += 1
        # kill MID-FLUSH: the next close's persist is in flight right
        # after a CLOSED line ~continuously; no sleep = maximum tear
        os.kill(child.pid, signal.SIGKILL)
        child.wait(timeout=30)
        resolved = verify_reopen(backend, state_dir)
        if resolved < MIN_RESOLVED:
            raise AssertionError(
                f"[{backend}] only {resolved} ledgers resolved "
                f"(need >= {MIN_RESOLVED}) — anti-vacuity"
            )
        print(f"  [{backend}] OK")
        return True
    except AssertionError as exc:
        print(f"STORAGE SMOKE FAILED: {exc}", file=sys.stderr)
        return False
    finally:
        if child.poll() is None:
            child.kill()
            child.wait(timeout=10)
        import shutil

        shutil.rmtree(state_dir, ignore_errors=True)


def main() -> int:
    if len(sys.argv) >= 4 and sys.argv[1] == "--child":
        child_flood(sys.argv[2], sys.argv[3])
        return 0
    backends = ["segstore"]
    # cpplog needs the native toolchain; skip cleanly where absent
    try:
        from stellard_tpu.native import load_native

        if load_native() is not None:
            backends.append("cpplog")
        else:
            print("  [cpplog] skipped: native toolchain unavailable")
    except Exception:  # noqa: BLE001
        print("  [cpplog] skipped: native toolchain unavailable")
    ok = True
    for backend in backends:
        print(f"== storage crash-recovery: {backend} ==", flush=True)
        ok = run_one(backend) and ok
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
