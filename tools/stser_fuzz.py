"""Byte-mutation fuzz corpus for the native STObject parser + proto2 codec.

Seeded from VALID blobs (a signed transaction, transaction metadata, a
trust-line SLE, a directory node, protobuf overlay messages), then
mutated deterministically: single/multi bit flips, truncations, length-
field lies (VL/varint prefixes bumped to claim more bytes than exist),
and random splices. The contract under fuzz is crash-freedom: every
case either parses or raises a Python exception — the process dying
(segfault, abort, ASAN report) is the failure signal.

Runs two ways:

- tests/test_stser_fuzz.py imports `run_corpus` for the CI-sized pass
  (~10^5 cases) against whatever parser stellard_tpu.protocol.stobject
  resolves (native _stser when buildable, pure Python otherwise);
- `make -C native fuzz-asan` rebuilds _stser.so with
  -fsanitize=address,undefined and drives THIS file as a script over the
  same corpus, with the sanitized extension forced in (STSER_PATH env),
  so heap overreads that happen to not crash the plain build still get
  caught.
"""

from __future__ import annotations

import os
import random
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

DEFAULT_CASES = int(os.environ.get("STSER_FUZZ_CASES", "100000"))
SEED = int(os.environ.get("STSER_FUZZ_SEED", "20260803"))


def _force_stser(path: str) -> None:
    """Force a specific _stser.so (e.g. the ASAN build) into the loader
    memo BEFORE protocol.stobject resolves it."""
    import importlib.machinery
    import importlib.util

    from stellard_tpu import native

    loader = importlib.machinery.ExtensionFileLoader("_stser", path)
    spec = importlib.util.spec_from_loader("_stser", loader)
    mod = importlib.util.module_from_spec(spec)
    loader.exec_module(mod)
    with native._lock:
        native._stser_mod = mod
        native._stser_tried = True


def seed_blobs() -> list[bytes]:
    """Valid serialized forms covering the grammar: VL fields, amounts
    (native + IOU), inner objects, arrays, account fields."""
    from stellard_tpu.protocol.formats import LedgerEntryType, TxType
    from stellard_tpu.protocol.keys import KeyPair
    from stellard_tpu.protocol.sfields import (
        sfAffectedNodes,
        sfAmount,
        sfBalance,
        sfDestination,
        sfFinalFields,
        sfFlags,
        sfHighLimit,
        sfIndexes,
        sfLedgerEntryType,
        sfLedgerIndex,
        sfLowLimit,
        sfModifiedNode,
        sfRootIndex,
        sfTransactionIndex,
        sfTransactionResult,
    )
    from stellard_tpu.protocol.stamount import STAmount
    from stellard_tpu.protocol.stobject import STArray, STObject
    from stellard_tpu.protocol.sttx import SerializedTransaction

    master = KeyPair.from_passphrase("masterpassphrase")
    dest = KeyPair.from_passphrase("fuzz-dest")
    usd = b"USD" + b"\x00" * 17

    tx = SerializedTransaction.build(
        TxType.ttPAYMENT, master.account_id, 7, 10,
        {sfAmount: STAmount.from_iou(usd, dest.account_id, 123456, -2),
         sfDestination: dest.account_id},
    )
    tx.sign(master)

    line = STObject()
    line[sfLedgerEntryType] = int(LedgerEntryType.ltRIPPLE_STATE)
    line[sfFlags] = 0x00110000
    line[sfBalance] = STAmount.from_iou(usd, b"\x00" * 19 + b"\x01", 5, 0)
    line[sfLowLimit] = STAmount.from_iou(usd, master.account_id, 10**9, 0)
    line[sfHighLimit] = STAmount.from_iou(usd, dest.account_id, 0, 0)

    dirnode = STObject()
    dirnode[sfLedgerEntryType] = int(LedgerEntryType.ltDIR_NODE)
    dirnode[sfRootIndex] = b"\x42" * 32
    dirnode[sfIndexes] = [bytes([i]) * 32 for i in range(5)]

    node = STObject()
    node[sfLedgerEntryType] = int(LedgerEntryType.ltACCOUNT_ROOT)
    node[sfLedgerIndex] = b"\x17" * 32
    fin = STObject()
    fin[sfBalance] = STAmount.from_drops(999_999)
    node[sfFinalFields] = fin
    affected = STArray()
    affected.append(sfModifiedNode, node)
    meta = STObject()
    meta[sfTransactionIndex] = 3
    meta[sfAffectedNodes] = affected
    meta[sfTransactionResult] = 0
    return [tx.serialize(), line.serialize(), dirnode.serialize(),
            meta.serialize()]


def proto_seed_blobs() -> list[bytes]:
    """Valid protobuf frames from the overlay codec."""
    from stellard_tpu.overlay.proto import Encoder

    hello = (
        Encoder()
        .varint(1, 10003)
        .varint(2, 1)
        .blob(3, b"\x02" + b"\x11" * 32)
        .blob(4, b"\x30" * 70)
        .varint(5, 40_000_000)
        .blob(6, b"\x99" * 32)
    )
    nested = Encoder().message(2, Encoder().varint(1, 7).blob(2, b"abc"))
    txm = Encoder().blob(1, b"\x12\x00\x22\x01\x00").varint(2, 1)
    return [hello.data(), nested.data(), txm.data()]


def mutate(rng: random.Random, blob: bytes) -> bytes:
    """One deterministic mutation: bit flip(s), truncation, length-field
    lie (byte bumped — VL prefixes and varints both live inline), or a
    splice of two regions."""
    b = bytearray(blob)
    kind = rng.randrange(5)
    if not b:
        return bytes(b)
    if kind == 0:  # single bit flip
        i = rng.randrange(len(b))
        b[i] ^= 1 << rng.randrange(8)
    elif kind == 1:  # burst of bit flips
        for _ in range(rng.randrange(2, 9)):
            i = rng.randrange(len(b))
            b[i] ^= 1 << rng.randrange(8)
    elif kind == 2:  # truncation
        b = b[: rng.randrange(len(b))]
    elif kind == 3:  # length-field lie: bump a byte to a large value
        i = rng.randrange(len(b))
        b[i] = rng.choice((0x7F, 0xC0, 0xF1, 0xFE, 0xFF))
    else:  # splice two regions (duplicates/reorders length prefixes)
        if len(b) >= 4:
            i, j = sorted(rng.randrange(len(b)) for _ in range(2))
            k = rng.randrange(len(b))
            b = b[:k] + b[i:j] + b[k:]
        else:
            b += bytes([rng.randrange(256)])
    return bytes(b)


def run_corpus(cases: int = DEFAULT_CASES, seed: int = SEED,
               progress: bool = False) -> dict:
    """Fuzz both parsers; returns outcome counts. Crash-freedom is the
    assertion — any Python exception is an accepted outcome."""
    from stellard_tpu.overlay import proto
    from stellard_tpu.protocol.stobject import STObject

    rng = random.Random(seed)
    st_seeds = seed_blobs()
    pb_seeds = proto_seed_blobs()
    counts = {"st_ok": 0, "st_err": 0, "pb_ok": 0, "pb_err": 0}
    n_st = cases * 3 // 4
    for i in range(cases):
        if i < n_st:
            blob = mutate(rng, rng.choice(st_seeds))
            try:
                STObject.from_bytes(blob)
                counts["st_ok"] += 1
            except Exception:  # noqa: BLE001 — rejection is a pass
                counts["st_err"] += 1
        else:
            blob = mutate(rng, rng.choice(pb_seeds))
            try:
                proto.parse(blob)
                counts["pb_ok"] += 1
            except Exception:  # noqa: BLE001 — rejection is a pass
                counts["pb_err"] += 1
        if progress and i and i % 20000 == 0:
            print(f"stser-fuzz: {i}/{cases} {counts}", flush=True)
    return counts


def main() -> int:
    forced = os.environ.get("STSER_PATH")
    if forced:
        _force_stser(os.path.abspath(forced))
    from stellard_tpu.protocol import stobject

    st = stobject._get_stser()
    print(f"stser-fuzz: native parser {'LOADED' if st else 'absent'}"
          f"{' (forced ' + forced + ')' if forced else ''}", flush=True)
    counts = run_corpus(progress=True)
    print(f"stser-fuzz: done {counts}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
