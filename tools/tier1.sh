#!/usr/bin/env bash
# tier-1 gate: the ROADMAP.md verify command PLUS a collect-only gate
# that fails on ANY collection error. The gate exists because a missing
# optional dependency once silently hid 29 of 33 test modules behind
# "errors during collection" while the visible tail still said "61
# passed" — a collection error must fail CI loudly, never shrink the
# suite quietly.
set -o pipefail
cd "$(dirname "$0")/.."

echo "== collect-only gate (0 errors required) =="
# no --continue-on-collection-errors here: any collection error exits
# non-zero (pytest rc 2) and fails the gate before the real run
if ! JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
    --collect-only -p no:cacheprovider >/tmp/_t1_collect.log 2>&1; then
  echo "COLLECTION ERRORS — failing tier-1 before the test run:" >&2
  grep -aE "^ERROR|ModuleNotFoundError|ImportError" /tmp/_t1_collect.log | head -40 >&2
  exit 2
fi
tail -1 /tmp/_t1_collect.log

echo "== trace smoke gate (flood -> trace_dump -> schema + span trees) =="
# boots a standalone node, floods ~200 txs through the full async
# pipeline, fetches trace_dump over the real HTTP RPC door, and
# validates the Chrome trace-event JSON AND the per-transaction causal
# span trees — a broken exporter fails tier-1, not a debugging session
if ! JAX_PLATFORMS=cpu timeout -k 10 300 python tools/traceview.py --smoke; then
  echo "TRACE SMOKE FAILED — trace_dump exporter is broken" >&2
  exit 2
fi

echo "== seal-equivalence smoke gate (incremental vs full seal) =="
# boots a node with the incremental seal on (default), floods 200 txs,
# and shadow-recomputes every close's ledger hash with a from-scratch
# full seal — a wrong pre-hashed node fails CI, not a consensus round
if ! JAX_PLATFORMS=cpu timeout -k 10 300 python tools/sealsmoke.py; then
  echo "SEAL SMOKE FAILED — incremental seal diverged from full seal" >&2
  exit 2
fi

echo "== parallel-speculation smoke gate (workers=4 flood vs serial shadow) =="
# boots a node with the Block-STM worker pool on (workers=4, process
# transport), floods 200 txs through the full async pipeline, then
# replays the identical workload through a workers=1 node: every close
# must match byte-for-byte and the splice rate must not regress — the
# parallel plane's byte-identity invariant is CI-gated per close
if ! JAX_PLATFORMS=cpu timeout -k 10 300 python tools/specsmoke.py; then
  echo "SPEC SMOKE FAILED — parallel speculation diverged from serial" >&2
  exit 2
fi

echo "== storage crash-recovery smoke gate (SIGKILL mid-flush -> reopen -> resolve) =="
# floods a file-backed node per durable backend (segstore, cpplog),
# SIGKILLs it mid-flush, reopens the stores, and asserts every ledger
# whose txdb header committed fully resolves (every node content-
# verified) — torn-tail recovery and the pipeline's durability ordering
# are CI-gated, not an ops-day discovery
if ! JAX_PLATFORMS=cpu timeout -k 10 500 python tools/storagesmoke.py; then
  echo "STORAGE SMOKE FAILED — crash recovery is broken" >&2
  exit 2
fi

echo "== out-of-core state smoke gate (lazy resume, tiny cache_mb, shard-served history) =="
# resumes a persisted chain with LAZY tree faulting under a deliberately
# tiny [tree] cache_mb and an effectively-unbounded one, floods 200 txs
# through each, and asserts: per-seq state/tx roots byte-identical,
# nonzero fault counters (anti-vacuity), bounded RSS growth, and — with
# online deletion + history shards on — a below-floor account_tx served
# from a sealed shard instead of lgrIdxInvalid
if ! JAX_PLATFORMS=cpu timeout -k 10 500 python tools/oocsmoke.py; then
  echo "OOC SMOKE FAILED — out-of-core state plane is broken" >&2
  exit 2
fi

echo "== multi-chip smoke gate (mesh-enabled verify flood vs cpu, byte identity) =="
# boots a node with [signature_backend] type=tpu mesh=auto
# routing=device on the virtual 8-device CPU mesh, floods 200 txs
# through the full async pipeline, and replays the identical workload
# on a cpu-backend node: every closed ledger hash must match
# byte-for-byte AND the mesh run must show device_sigs > 0 at
# effective width 8 — a sharded plane that silently fell back to the
# host (or flipped one verdict) fails CI, not a consensus round
if ! JAX_PLATFORMS=cpu timeout -k 10 600 python tools/meshsmoke.py; then
  echo "MESH SMOKE FAILED — sharded crypto plane is broken" >&2
  exit 2
fi

echo "== adversarial scenario smoke gate (partition + byzantine + catch-up, seeded) =="
# replays three deterministic simnet scenarios twice each with one
# seed: honest validators must converge on ONE identical chain, the two
# runs must produce byte-identical scorecards (a wall clock or unseeded
# RNG leaking into the deterministic transport fails here), and the
# hostile inputs must leave counter evidence (anti-vacuity) — byzantine
# defenses, catch-up retry/backoff/garbage-fallback, partition drops
if ! JAX_PLATFORMS=cpu timeout -k 10 300 python tools/scenariosmoke.py; then
  echo "SCENARIO SMOKE FAILED — adversarial plane is broken" >&2
  exit 2
fi

echo "== scenario fuzz smoke gate (corpus replay + armed sweep + shrink + coverage bias) =="
# the scenario-search plane end to end, seeded and bounded: (1) every
# checked-in minimal-repro corpus entry (the real bugs earlier sweeps
# found, fixed, and pinned) replays CLEAN through build_scenario;
# (2) a coverage-guided sweep with the planted test-only bug ARMED must
# FIND it within the budget and SHRINK it to its known minimal schedule
# (two plant events, magnitudes summing to the threshold, every other
# axis stripped); (3) the shrunk entry reproduces deterministically
# while armed and replays clean once disarmed — the found->shrunk->
# fixed->pinned loop; (4) any NON-synthetic violation is a new real bug
# and fails the gate; (5) coverage-guided generation must reach at
# least as many distinct scorecard dynamics states as uniform random
# over the same budget. FUZZ_N (default 30) is the per-phase budget —
# raise it for longer offline sweeps (e.g. FUZZ_N=300 overnight).
# wall-clock cap scales with the budget (~130s at the default 30)
FUZZ_N="${FUZZ_N:-30}"
FUZZ_TIMEOUT=$((120 + FUZZ_N * 16))
if ! JAX_PLATFORMS=cpu timeout -k 10 "$FUZZ_TIMEOUT" env FUZZ_N="$FUZZ_N" \
    python tools/scenariofuzz.py --smoke; then
  echo "FUZZ SMOKE FAILED — scenario search plane is broken (or found a real bug)" >&2
  exit 2
fi

echo "== overlay flood smoke gate (200-peer simnet, byzantine flooder -> DROP, squelch bound) =="
# runs the flood_survival scenario (5-validator core + 195 relay peers,
# squelched relay, enforced resource pricing, one hostile flooder)
# twice on one seed: honest validators converge on ONE hash with the
# full workload committed, the flooder's endpoint reaches DROP at every
# flooded neighbor and is refused readmission (resource.* counters),
# relay fan-out stays <= squelch_size + |UNL| (never the peer count),
# close cadence holds within 25% of the no-flooder baseline, and the
# scorecards are byte-identical across runs
if ! JAX_PLATFORMS=cpu timeout -k 10 300 python tools/floodsmoke.py; then
  echo "FLOOD SMOKE FAILED — overlay defense plane is broken" >&2
  exit 2
fi

echo "== follower tree smoke gate (leader <- F1 <- F2 cascade over TCP, identity + resume) =="
# boots a solo leader and a depth-2 follower cascade (F1 pinned to the
# leader, F2 pinned to F1 — the leader holds exactly ONE peer session,
# its egress is O(children) not O(followers)), floods the leader, and
# asserts: BOTH tiers' ledger hashes byte-identical to the leader's at
# every validated seq, F2 cold-syncs through F1's epoch-stamped sealed
# shards (snapshot handoff via the GetSegments door), read RPCs served
# from F1 mid-flood with the validated-seq cache hitting, a dropped
# subscriber on F2 resuming from its seq cursor with zero gap while a
# past-horizon cursor gets the explicit cold answer, and zero consensus
# rounds on either follower
if ! JAX_PLATFORMS=cpu timeout -k 10 300 python tools/followersmoke.py; then
  echo "FOLLOWER SMOKE FAILED — read-plane tier is broken" >&2
  exit 2
fi

echo "== archive tier smoke gate (hostile then honest backfill over TCP, deep-history byte match) =="
# boots a solo leader with online deletion + history shards, floods it
# until deep history exists ONLY in sealed shard files, then runs the
# archive tier twice: against a byte-flipping upstream (every poisoned
# image rejected at the verify gate, the peer resource-charged AND
# excluded, ZERO hostile bytes retained) and against the honest leader
# (>=2 shards backfilled over the wire from cold start, deep
# account_tx/tx/ledger served below the leader's retain floor with
# every row byte-matched against the sealed shard contents, the
# forever-tier result cache taking hits on immutable windows)
if ! JAX_PLATFORMS=cpu timeout -k 10 300 python tools/archivesmoke.py; then
  echo "ARCHIVE SMOKE FAILED — archive tier / shard distribution network is broken" >&2
  exit 2
fi

echo "== liquidity-plane smoke gate (crossfire flood, live path subs, incremental==full) =="
# boots a node with the paths plane on (default), floods an order-book
# crossfire (creates, tier-consuming crossings, cancels) with N live
# path_find subscriptions plus a resource-throttled path-spam flooder,
# and asserts per close: the incremental book index byte-equals a full
# state scan (with the incremental path provably engaged), every close
# re-ranks and delivers subscription updates, the flooder is shed by
# the resource plane, and close cadence holds vs the no-subs baseline
if ! JAX_PLATFORMS=cpu timeout -k 10 300 python tools/pathsmoke.py; then
  echo "PATH SMOKE FAILED — liquidity plane is broken" >&2
  exit 2
fi

echo "== overload-admission smoke gate (4x flood -> bounded closes, fee-order drain) =="
# boots a node with a pinned small admission cap, floods it at 4x that
# capacity through the full async pipeline, and asserts the RPC door
# stays responsive, no close exceeds the cap, the queue drains in fee
# order, and the held pile never grows — overload behavior is CI-gated,
# not a bench-day anecdote
if ! JAX_PLATFORMS=cpu timeout -k 10 300 python tools/overload_smoke.py; then
  echo "OVERLOAD SMOKE FAILED — admission-control plane is broken" >&2
  exit 2
fi

echo "== observability smoke gate (leader+2 followers, merged trace, /metrics, stall -> warn) =="
# boots a leader and two followers over real TCP with sampling at 1.0
# and propagation on, floods the leader, and asserts the PR-18 plane:
# a merged Perfetto trace with >=1 tx spanning all 3 process lanes,
# /metrics scrapes clean mid-flood, propagate=0 stays byte-identical on
# the wire, and an injected cadence stall flips the health watchdog to
# warn and ships a flight-recorder dump
if ! JAX_PLATFORMS=cpu timeout -k 10 300 python tools/obsmoke.py; then
  echo "OBSERVABILITY SMOKE FAILED — cross-node tracing / health plane is broken" >&2
  exit 2
fi

echo "== tier-1 test run (ROADMAP.md command) =="
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
  --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly \
  2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)
[ $rc -ne 0 ] && exit $rc

# --full: additionally run the slow-marked wall-clock-heavy corpus
# (kernel differentials, soaks) with no 870s cap — the deep gate the
# tier-1 budget cannot afford on every run
if [ "${1:-}" = "--full" ]; then
  echo "== slow corpus (-m slow, uncapped) =="
  JAX_PLATFORMS=cpu python -m pytest tests/ -q -m slow \
    --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly
  rc=$?
fi
exit $rc
