"""Background TPU-tunnel watcher: probe until the chip answers, then sweep.

The axon tunnel wedges for hours at a time (jax.devices() HANGS rather
than erroring), so every probe runs in a throwaway subprocess with a hard
wall-clock timeout, and only ONE TPU-touching process ever runs at a time
(concurrent sessions are what wedge it). When a probe succeeds this runs
`tools/kernel_sweep.py` and then `bench.py`, logging to LOG, and exits.

Usage: nohup python tools/tpu_watcher.py > /tmp/tpu_watcher.log 2>&1 &
"""
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LOG = "/root/repo/SWEEP_r04.log"
PROBE_TIMEOUT = 120
PROBE_INTERVAL = 300


def probe() -> bool:
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.devices()[0].platform)"],
            capture_output=True, text=True, timeout=PROBE_TIMEOUT, env=env,
        )
    except subprocess.TimeoutExpired:
        return False
    return r.returncode == 0 and "tpu" in r.stdout.lower()


def main() -> None:
    n = 0
    while True:
        n += 1
        up = probe()
        print(f"[watcher] probe {n}: {'UP' if up else 'down'} "
              f"({time.strftime('%H:%M:%S')})", flush=True)
        if up:
            break
        time.sleep(PROBE_INTERVAL)
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    with open(LOG, "a") as f:
        f.write(f"=== tunnel up at {time.strftime('%F %T')}; sweeping ===\n")
        f.flush()
        subprocess.run([sys.executable, os.path.join(REPO, "tools/kernel_sweep.py")],
                       stdout=f, stderr=subprocess.STDOUT, cwd=REPO, env=env)
        f.write("=== sweep done; running bench.py ===\n")
        f.flush()
        subprocess.run([sys.executable, os.path.join(REPO, "bench.py")],
                       stdout=f, stderr=subprocess.STDOUT, cwd=REPO, env=env)
        f.write("=== bench done ===\n")
    print("[watcher] sweep+bench complete; see", LOG, flush=True)


if __name__ == "__main__":
    main()
