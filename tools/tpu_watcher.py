"""Background TPU-tunnel watcher: probe until the chip answers, then measure.

The axon tunnel wedges for hours at a time (jax.devices() HANGS rather
than erroring), so every probe runs in a throwaway subprocess with a hard
wall-clock timeout, and only ONE TPU-touching process ever runs at a time
(concurrent sessions are what wedge it). When a probe succeeds this runs
`bench.py` FIRST (the end-to-end device legs are the round's headline
evidence and KERNEL_TUNING already pins a measured-good config — a short
window must capture them) and then `tools/kernel_sweep.py` (upside-only
A/B), logging to LOG; it exits only once a cycle shows both an on-chip
bench line and a verify-sweep RESULT row.

Usage: nohup python tools/tpu_watcher.py > /tmp/tpu_watcher.log 2>&1 &
"""
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LOG = os.path.join(REPO, "SWEEP_r05.log")
PROBE_TIMEOUT = 120
# a wedged probe HANGS its full timeout, so the down-cycle is already
# PROBE_TIMEOUT + interval; r4's windows were as short as ~8 min, and a
# 300s interval can eat half a window before the first UP probe lands.
# Each probe also burns ~25s of this 1-core box on the jax import, so
# the interval is a contention/detection-latency tradeoff (~9% duty).
PROBE_INTERVAL = 150
RUN_TIMEOUT = 5400  # sweep/bench can compile for ~3min/shape; a wedge hangs forever


def probe() -> bool:
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.devices()[0].platform)"],
            capture_output=True, text=True, timeout=PROBE_TIMEOUT, env=env,
        )
    except subprocess.TimeoutExpired:
        return False
    return r.returncode == 0 and "tpu" in r.stdout.lower()


def _run_logged(f, label: str, argv: list[str], env) -> bool:
    """One sweep/bench subprocess with a hard wall-clock timeout — the
    tunnel's failure mode is an indefinite HANG, so an untimed run would
    wedge the watcher (and, as the single allowed TPU process, block all
    probing) forever."""
    f.write(f"=== {label} at {time.strftime('%F %T')} ===\n")
    f.flush()
    try:
        subprocess.run(argv, stdout=f, stderr=subprocess.STDOUT, cwd=REPO,
                       env=env, timeout=RUN_TIMEOUT)
    except subprocess.TimeoutExpired:
        f.write(f"=== {label} TIMED OUT after {RUN_TIMEOUT}s (wedged tunnel) ===\n")
        f.flush()
        return False
    f.write(f"=== {label} done ===\n")
    f.flush()
    return True


def main() -> None:
    # hard lifetime cap: an unattended watcher that never sees the tunnel
    # must not still be burning this 1-core box (each probe is a full jax
    # import) when the driver's own end-of-round bench runs
    stop_after = float(os.environ.get("WATCHER_MAX_S", str(10.0 * 3600)))
    t_start = time.monotonic()
    n = 0
    while True:
        if time.monotonic() - t_start > stop_after:
            print("[watcher] lifetime cap reached without a full on-chip "
                  "cycle; exiting", flush=True)
            return
        n += 1
        up = probe()
        print(f"[watcher] probe {n}: {'UP' if up else 'down'} "
              f"({time.strftime('%H:%M:%S')})", flush=True)
        if not up:
            time.sleep(PROBE_INTERVAL)
            continue
        env = dict(os.environ)
        env.pop("JAX_PLATFORMS", None)
        mark = os.path.getsize(LOG) if os.path.exists(LOG) else 0
        with open(LOG, "a") as f:
            # bench FIRST: KERNEL_TUNING already pins a measured-good
            # config, and the end-to-end device legs are the round's
            # headline evidence — a short tunnel window must capture
            # them before the (longer, upside-only) sweep. The driver's
            # own end-of-round bench picks up any tuning the sweep
            # improves afterwards.
            ok = _run_logged(
                f, "bench", [sys.executable, os.path.join(REPO, "bench.py")], env,
            ) and _run_logged(
                f, "kernel_sweep",
                [sys.executable, os.path.join(REPO, "tools/kernel_sweep.py")], env,
            )
        if ok:
            # both subprocesses finished — but a mid-run wedge makes the
            # sweep skip configs (exit 0) and bench emit its CPU-fallback
            # lines (exit 0), which is NOT the measurement this watcher
            # exists to capture. Stop only when the cycle produced BOTH
            # a verify-sweep measurement (a "RESULT unroll=" row, not
            # just a treehash row) AND at least one on-chip bench line;
            # a single leg's fallback must not discard a good cycle.
            with open(LOG) as f:
                f.seek(mark)
                tail = f.read()
            if "RESULT unroll=" in tail and '"fallback": false' in tail:
                break
            print("[watcher] cycle completed but without on-chip sweep+"
                  "bench evidence (wedge mid-run) — continuing to probe",
                  flush=True)
        time.sleep(PROBE_INTERVAL)
    print("[watcher] sweep+bench complete; see", LOG, flush=True)


if __name__ == "__main__":
    main()
