#!/usr/bin/env python
"""traceview: fetch, validate, and save a node's `trace_dump`.

The tracing plane (stellard_tpu/node/tracer.py) exports Chrome
trace-event JSON through the `trace_dump` admin RPC. This tool wraps the
three things an operator (and the tier-1 gate) needs around that RPC:

  fetch     POST trace_dump to a node's HTTP RPC door and save the
            trace to a file Perfetto / chrome://tracing loads directly:
                python tools/traceview.py --url http://127.0.0.1:5005 \\
                    -o trace.json
  validate  schema-check an already-saved dump:
                python tools/traceview.py --validate trace.json
  smoke     boot an in-process standalone node, flood ~200 transactions
            through the full async pipeline, close ledgers, fetch
            trace_dump over the REAL HTTP door, validate the JSON
            schema AND the causal span tree per transaction
            (submit → verify → close → persist):
                python tools/traceview.py --smoke
  merge     fetch trace_dump from N nodes and emit ONE Perfetto file
            with a process lane per node — cross-node trace propagation
            ([trace] propagate=1) makes spans on different nodes share
            trace/parent ids, so a sampled tx renders as one causal
            tree across lanes:
                python tools/traceview.py --merge \\
                    http://127.0.0.1:5005 http://127.0.0.1:5006 \\
                    -o merged.json

The schema validator is hand-rolled (no jsonschema dependency) against
the trace-event format's documented requirements; `validate_chrome_trace`,
`validate_span_trees`, `merge_dumps` and `validate_merged_trace` are
importable by tests.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import urllib.request

# runnable as "python tools/traceview.py" from anywhere: a script in
# tools/ does not get the repo root on sys.path by itself
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# phases from the trace-event format spec (Duration, Complete, Instant,
# Counter, Async, Flow, Sample, Object, Metadata, Memory-dump, Mark,
# Clock-sync, Context)
_KNOWN_PHASES = set("BEXiICbnesftPOoNDMVvRcGT(),")
_INSTANT_SCOPES = {"g", "p", "t"}


def validate_chrome_trace(obj) -> list[str]:
    """-> list of schema problems (empty = valid Chrome trace-event
    JSON). Checks the object form: {"traceEvents": [events...], ...}."""
    problems: list[str] = []
    if not isinstance(obj, dict):
        return [f"top level must be an object, got {type(obj).__name__}"]
    events = obj.get("traceEvents")
    if not isinstance(events, list):
        return ["missing or non-array traceEvents"]
    for i, ev in enumerate(events):
        where = f"traceEvents[{i}]"
        if not isinstance(ev, dict):
            problems.append(f"{where}: event must be an object")
            continue
        ph = ev.get("ph")
        if not isinstance(ph, str) or len(ph) != 1 or ph not in _KNOWN_PHASES:
            problems.append(f"{where}: bad phase {ph!r}")
            continue
        name = ev.get("name")
        if ph != "M" and not isinstance(name, str):
            problems.append(f"{where}: missing/non-string name")
        ts = ev.get("ts")
        if not isinstance(ts, (int, float)) or ts < 0:
            problems.append(f"{where}: missing/negative ts")
        for key in ("pid", "tid"):
            if not isinstance(ev.get(key), int):
                problems.append(f"{where}: missing/non-integer {key}")
        if ph == "X":
            dur = ev.get("dur")
            if not isinstance(dur, (int, float)) or dur < 0:
                problems.append(f"{where}: complete event needs dur >= 0")
        if ph == "i" and ev.get("s") not in _INSTANT_SCOPES:
            problems.append(f"{where}: instant scope must be g/p/t")
        if "cat" in ev and not isinstance(ev["cat"], str):
            problems.append(f"{where}: non-string cat")
        if "args" in ev and not isinstance(ev["args"], dict):
            problems.append(f"{where}: non-object args")
        if len(problems) > 40:
            problems.append("... (truncated)")
            break
    return problems


def validate_span_trees(obj, require_stages=(
    "submit", "verify", "close", "persist",
)) -> list[str]:
    """Check the causal structure the tracing plane promises: every
    transaction trace present in the dump carries events for (at least)
    the given lifecycle stages, and child spans resolve their parent
    ids. A tx trace id is the 64-hex txid; ledger traces are
    "ledger-<seq>"."""
    problems: list[str] = []
    by_trace: dict[str, list[dict]] = {}
    span_ids = set()
    for ev in obj.get("traceEvents", []):
        args = ev.get("args") or {}
        if "span" in args:
            span_ids.add(args["span"])
        trace = args.get("trace")
        if isinstance(trace, str) and len(trace) == 64:
            by_trace.setdefault(trace, []).append(ev)
    if not by_trace:
        return ["no transaction traces in dump"]
    for trace, evs in by_trace.items():
        cats = {ev.get("cat") for ev in evs}
        missing = [c for c in require_stages if c not in cats]
        if missing:
            problems.append(
                f"tx {trace[:16]}: missing stages {missing} (has {sorted(cats)})"
            )
        for ev in evs:
            args = ev.get("args") or {}
            parent = args.get("parent")
            if args.get("remote"):
                # cross-node adoption: the parent span lives in ANOTHER
                # node's ring — unresolvable by design in a single-node
                # dump (the merge validator checks it across dumps)
                continue
            if parent is not None and parent not in span_ids:
                problems.append(
                    f"tx {trace[:16]}: span {args.get('span')} "
                    f"references unknown parent {parent}"
                )
    return problems


# -- cross-node merge (tentpole leg 1) --------------------------------------


def merge_dumps(dumps: list[tuple[str, dict]]) -> dict:
    """N per-node `trace_dump` objects -> ONE Chrome trace with a
    process lane per node. Span/parent ids need NO remapping: the
    tracer folds a 32-bit node tag into the high half of every span id,
    so ids from different nodes never collide and cross-node parent
    links resolve as-is. Timestamps stay per-node (each tracer's epoch
    is process-local) — lanes align structurally, not on a shared
    clock."""
    events: list[dict] = []
    other: dict[str, dict] = {}
    for pid, (label, dump) in enumerate(dumps, start=1):
        events.append({
            "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "ts": 0, "args": {"name": label},
        })
        for ev in dump.get("traceEvents", []):
            ev = dict(ev)
            ev["pid"] = pid
            events.append(ev)
        other[label] = dump.get("otherData", {})
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": other,
    }


def validate_merged_trace(obj, min_processes: int = 3) -> list[str]:
    """Check what cross-node propagation promises a MERGED dump: at
    least one sampled tx has events in >= min_processes distinct
    process lanes, every cross-node parent link resolves somewhere in
    the merged dump, and each such tx's causal tree is single-rooted
    (exactly one CONNECTED root — a span with children but no parent;
    orphan instants with neither don't count as roots)."""
    problems: list[str] = []
    events = obj.get("traceEvents", [])
    all_spans = set()
    by_trace: dict[str, list[dict]] = {}
    for ev in events:
        if ev.get("ph") == "M":
            continue
        args = ev.get("args") or {}
        if "span" in args:
            all_spans.add(args["span"])
        trace = args.get("trace")
        if isinstance(trace, str) and len(trace) == 64:
            by_trace.setdefault(trace, []).append(ev)
    if not by_trace:
        return ["no transaction traces in merged dump"]
    wide = 0
    for trace, evs in sorted(by_trace.items()):
        pids = {ev.get("pid") for ev in evs}
        spans: dict[int, object] = {}
        for ev in evs:
            a = ev.get("args") or {}
            if a.get("span") is not None:
                spans.setdefault(a["span"], a.get("parent"))
        for s, p in spans.items():
            if p is not None and p not in all_spans:
                problems.append(
                    f"tx {trace[:16]}: span {s} parent {p} unresolved "
                    f"in the merged dump"
                )
        if len(pids) < min_processes:
            continue
        wide += 1
        referenced = {p for p in spans.values() if p is not None}
        roots = sorted(
            s for s, p in spans.items() if p is None and s in referenced
        )
        if not roots:
            problems.append(
                f"tx {trace[:16]}: no connected root span "
                f"({len(pids)} processes)"
            )
        elif len(roots) > 1:
            problems.append(
                f"tx {trace[:16]}: multi-rooted causal tree "
                f"({len(roots)} roots across {len(pids)} processes)"
            )
    if wide == 0:
        problems.append(
            f"no tx trace spans >= {min_processes} processes "
            f"(propagation broken or sampling disjoint)"
        )
    return problems


def fetch_dump(url: str, reset: bool = False, timeout: float = 30.0) -> dict:
    """POST trace_dump to a node's HTTP RPC door; -> the trace object."""
    body = json.dumps({
        "method": "trace_dump",
        "params": [{"reset": bool(reset)}],
    }).encode()
    req = urllib.request.Request(
        url, data=body, headers={"Content-Type": "application/json"}
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        reply = json.loads(resp.read())
    result = reply.get("result", {})
    if result.get("status") != "success":
        raise RuntimeError(f"trace_dump failed: {result}")
    result.pop("status", None)  # transport envelope, not trace data
    return result


# -- smoke gate (tier-1) ----------------------------------------------------


def run_smoke(n_txs: int = 200, out: str | None = None) -> int:
    """Boot a standalone node, flood `n_txs` payments through the full
    async pipeline, close every 50, fetch trace_dump over the real HTTP
    door, and fail loudly unless (a) the JSON validates against the
    trace-event schema and (b) every transaction trace carries its
    submit/verify/close/persist stages with resolvable parent links."""
    import threading

    from stellard_tpu.node.config import Config
    from stellard_tpu.node.node import Node
    from stellard_tpu.protocol.formats import TxType
    from stellard_tpu.protocol.keys import KeyPair
    from stellard_tpu.protocol.sfields import sfAmount, sfDestination
    from stellard_tpu.protocol.stamount import STAmount
    from stellard_tpu.protocol.sttx import SerializedTransaction

    # sample=1.0: the smoke asserts EVERY tx has its full span tree
    node = Node(Config(rpc_port=0, trace_sample=1.0)).setup().serve()
    try:
        master = KeyPair.from_passphrase("masterpassphrase")
        dests = [
            KeyPair.from_passphrase(f"trace-smoke-{i}").account_id
            for i in range(8)
        ]
        done = threading.Semaphore(0)
        results = []

        def cb(tx, ter, applied):
            results.append((ter, applied))
            done.release()

        for chunk in range(0, n_txs, 50):
            txs = []
            for i in range(chunk, min(chunk + 50, n_txs)):
                tx = SerializedTransaction.build(
                    TxType.ttPAYMENT, master.account_id, 1 + i, 10,
                    {sfAmount: STAmount.from_drops(250_000_000),
                     sfDestination: dests[i % len(dests)]},
                )
                tx.sign(master)
                txs.append(tx)
            for tx in txs:
                node.ops.submit_transaction(tx, cb)
            for _ in txs:
                done.acquire()
            node.ops.accept_ledger()
        if not node.close_pipeline.flush(timeout=60):
            print("trace smoke: close pipeline failed to drain", file=sys.stderr)
            return 1

        url = f"http://127.0.0.1:{node.http_server.port}"
        dump = fetch_dump(url)
    finally:
        node.stop()

    problems = validate_chrome_trace(dump)
    if problems:
        print("trace smoke: SCHEMA INVALID:", file=sys.stderr)
        for p in problems[:20]:
            print(f"  - {p}", file=sys.stderr)
        return 1
    tree_problems = validate_span_trees(dump)
    if tree_problems:
        print("trace smoke: SPAN TREES BROKEN:", file=sys.stderr)
        for p in tree_problems[:20]:
            print(f"  - {p}", file=sys.stderr)
        return 1
    events = dump["traceEvents"]
    traces = {
        (ev.get("args") or {}).get("trace")
        for ev in events
        if len((ev.get("args") or {}).get("trace") or "") == 64
    }
    if out:
        with open(out, "w") as fh:
            json.dump(dump, fh)
    print(
        f"trace smoke OK: {len(events)} events, {len(traces)} tx traces, "
        f"schema valid, span trees causally linked"
    )
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--url", help="node RPC door, e.g. http://127.0.0.1:5005")
    ap.add_argument("--validate", metavar="FILE",
                    help="validate an already-saved dump file")
    ap.add_argument("--smoke", action="store_true",
                    help="in-process end-to-end gate (tier-1)")
    ap.add_argument("--merge", nargs="+", metavar="URL",
                    help="fetch trace_dump from N nodes, emit one "
                         "Perfetto file with a lane per node")
    ap.add_argument("--min-processes", type=int, default=3,
                    help="merge: require >=1 tx spanning this many "
                         "process lanes (default 3)")
    ap.add_argument("--reset", action="store_true",
                    help="clear the node's ring after dumping")
    ap.add_argument("-o", "--out", help="write the trace JSON here")
    ap.add_argument("-n", type=int, default=200,
                    help="smoke: transactions to flood (default 200)")
    args = ap.parse_args(argv)

    if args.smoke:
        return run_smoke(n_txs=args.n, out=args.out)
    if args.merge:
        dumps = [
            (url, fetch_dump(url, reset=args.reset)) for url in args.merge
        ]
        merged = merge_dumps(dumps)
        problems = validate_chrome_trace(merged)
        problems += validate_merged_trace(
            merged, min_processes=min(args.min_processes, len(dumps))
        )
        for p in problems[:30]:
            print(f"  - {p}", file=sys.stderr)
        if args.out:
            with open(args.out, "w") as fh:
                json.dump(merged, fh)
            print(
                f"wrote {len(merged['traceEvents'])} events from "
                f"{len(dumps)} nodes to {args.out} "
                f"({'valid' if not problems else 'INVALID'})"
            )
        return 0 if not problems else 1
    if args.validate:
        with open(args.validate) as fh:
            obj = json.load(fh)
        problems = validate_chrome_trace(obj)
        for p in problems:
            print(f"  - {p}", file=sys.stderr)
        print("valid" if not problems else f"{len(problems)} problems")
        return 0 if not problems else 1
    if args.url:
        dump = fetch_dump(args.url, reset=args.reset)
        problems = validate_chrome_trace(dump)
        for p in problems:
            print(f"  - {p}", file=sys.stderr)
        if args.out:
            with open(args.out, "w") as fh:
                json.dump(dump, fh)
            print(f"wrote {len(dump.get('traceEvents', []))} events to "
                  f"{args.out} ({'valid' if not problems else 'INVALID'})")
        return 0 if not problems else 1
    ap.print_help()
    return 2


if __name__ == "__main__":
    sys.exit(main())
